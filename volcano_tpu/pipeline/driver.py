"""Continuous scheduling pipeline — double-buffered sessions with
speculative solve-ahead (ROADMAP item 2: sessions/sec as the headline).

The serial loop runs snapshot -> actions -> effectors -> close strictly
in sequence, so the device idles while the host closes a session and the
host idles while the device solves. This driver overlaps the phases of
CONSECUTIVE cycles instead, on one host thread (determinism — the only
concurrency is the device's own async execution):

    apply N   -> open N+1 (buffer swap, delta-open) -> dispatch N+1
              -> close N  (status writebacks, JobUpdater — overlapped
                           with N+1's device solve)
              -> [inter-cycle work: controllers, express, waits]
    cycle N+1 -> fingerprint check -> apply N+1 (speculation held)
                                   or discard + re-run (state moved)

Double buffer: the SnapshotKeeper's buffer pair (snapkeeper.py
enable_pair/swap) gives session N+1 its own clone set while session N's
close still reads its snapshot; every cache mark lands in both buffers'
dirty sets, so each buffer delta-maintains independently.

Speculation contract: cycle N+1's session is opened and its packed
rounds solve dispatched BEFORE cycle N's close (whose status writebacks
could, in principle, change state) and before any inter-cycle delta. A
delta fingerprint — the keeper's dirty epoch + generation, the lease
fence epoch, the summed cache-node accounting generation, and the
express lane's commit epoch — is sealed at dispatch and re-checked
before apply. ANY movement means the speculative snapshot is stale: the
stage is discarded (never fetched into session state, counted per reason
as ``pipeline_spec_discard{reason}``) and the cycle re-runs
non-speculatively on fresh state — which is exactly the serial order, so
the serial loop (``VOLCANO_TPU_PIPELINE=0``) stays the byte-for-byte
oracle whether speculation is on, off (``VOLCANO_TPU_PIPELINE_SPEC=0``),
held, or discarded.

Enqueue runs STAGED in a speculative session: the real EnqueueAction
executes, the Pending->Inqueue flips (which land on the SHARED PodGroup
objects) are recorded and immediately reverted, and they re-apply only
at commit time — a discarded speculative session must leave zero
observable state. A staged flip whose job already has pending tasks
would change what the solve encodes (the serial order admits it before
allocate), so that cycle declines to speculate (``enqueue_active``)
instead of risking parity. Under delayed pod creation (the production
admission gate) this never triggers in steady state.

Envelope: the pipelined fast path covers action chains of the shape
``[enqueue,] allocate[, backfill]`` whose allocate runs the packed rounds
solve (solver._prepare/parse_packed/apply_packed are the stage
boundaries). Anything else — preempt/reclaim chains (the fused
session dispatch owns those), serial-fallback sessions, custom plugins —
runs through the ordinary ``framework.run_actions`` per cycle, unpipelined
but correct (``fallback_cycles``). Repeated pipelined-cycle ERRORS open
the degrade ladder's ``pipeline_disabled`` breaker and the scheduler loop
reverts to serial run_once until the half-open probe passes.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.framework import (
    close_session,
    get_action,
    open_session,
    run_actions,
    takeover_recovery_sweep,
)

logger = logging.getLogger(__name__)

# the pipelined chain grammar: allocate, optionally preceded by enqueue
# and followed by backfill — the packed rounds solve is the single device
# stage whose dispatch can run ahead of the previous cycle's close
_CHAIN = ("enqueue", "allocate", "backfill")


def pipeline_enabled() -> bool:
    """VOLCANO_TPU_PIPELINE=0 forces the serial loop (the oracle)."""
    return os.environ.get("VOLCANO_TPU_PIPELINE", "1") != "0"


def speculation_enabled() -> bool:
    """VOLCANO_TPU_PIPELINE_SPEC=0 keeps the pipelined loop but never
    dispatches ahead (double-buffer-only mode)."""
    return os.environ.get("VOLCANO_TPU_PIPELINE_SPEC", "1") != "0"


class _InFlight:
    """One speculative solve-ahead: the early-opened session, its
    prepared packed dispatch, the sealed fingerprint, and the staged
    enqueue flips that re-apply only at commit."""

    __slots__ = ("ssn", "names", "prep", "dev", "wait", "fingerprint",
                 "flips", "tiers", "t_dispatch")

    def __init__(self, ssn, names, prep, dev, wait, fingerprint, flips,
                 tiers, t_dispatch):
        self.ssn = ssn
        self.names = names
        self.prep = prep
        self.dev = dev
        self.wait = wait
        self.fingerprint = fingerprint
        self.flips = flips
        self.tiers = tiers
        self.t_dispatch = t_dispatch


class PipelineDriver:
    """The pipelined cycle driver for one SchedulerCache.

    ``policy_fn`` returns the cycle's (actions, tiers); the TIERS OBJECT
    IDENTITY is part of the speculation fingerprint, so callers must hand
    back the same object while the conf is unchanged (Scheduler caches
    its parse on the conf text; the sim's conf is fixed).
    """

    # rolling window for the sustained sessions/sec gauge
    _RATE_WINDOW = 32

    def __init__(self, cache, policy_fn: Callable[[], Tuple[list, list]],
                 degrade=None, spec: Optional[bool] = None,
                 intake: Optional[Callable[[], None]] = None):
        self.cache = cache
        self.policy_fn = policy_fn
        # None => the process-default ladder, resolved LAZILY per use:
        # degrade.reset() (sim runs, tests) swaps the default instance,
        # and a driver built before the reset must not gate on the stale
        # one
        self._degrade = degrade
        self.spec = speculation_enabled() if spec is None else spec
        # intake: drained AFTER the cycle commits and BEFORE the next
        # cycle's snapshot seals — the watch-ingest quantization point.
        # A driver (bench --pipeline, an embedder pumping a delta queue)
        # that funnels arrivals through it makes them visible to the very
        # next speculative snapshot instead of invalidating it mid-flight;
        # deltas that bypass it (live watch events, express commits) are
        # still caught by the fingerprint and discard the stage.
        self.intake = intake
        cache.enable_pipeline()
        self._inflight: Optional[_InFlight] = None
        self._cycle_walls: List[float] = []
        self.stats: Dict[str, object] = {
            "cycles": 0, "committed": 0, "fallback_cycles": 0,
            "spec_dispatched": 0, "spec_applied": 0, "spec_discarded": 0,
            "spec_reruns": 0, "stale_commits": 0,
            "spec_discards": {}, "spec_skips": {},
        }

    @property
    def degrade(self):
        if self._degrade is not None:
            return self._degrade
        from volcano_tpu.scheduler import degrade as degrade_mod

        return degrade_mod.default_ladder()

    # -- fingerprint ---------------------------------------------------------

    def _fingerprint(self, tiers) -> tuple:
        from volcano_tpu.scheduler.plugins import tpuscore

        lane = getattr(self.cache, "express_lane", None)
        return (self.cache.pipeline_fingerprint(),
                lane.commit_epoch if lane is not None else -1,
                id(tiers),
                # mesh identity (device count + shard spec): a sealed
                # stage dispatched under one mesh shape is MIS-SHARDED
                # for any other — its packed buffers, window ladder and
                # padded node extent all keyed off the old device count
                tpuscore.mesh_fingerprint())

    def _check(self, st: _InFlight, tiers) -> Tuple[bool, str]:
        now = self._fingerprint(tiers)
        old = st.fingerprint
        if now == old:
            return True, ""
        # attribute the discard to the first component that moved — the
        # metric label operators alert on
        (o_cache, o_epoch, o_tiers, o_mesh) = old
        (n_cache, n_epoch, n_tiers, n_mesh) = now
        if o_mesh != n_mesh:
            return False, "mesh"
        if o_tiers != n_tiers:
            return False, "conf_changed"
        if o_epoch != n_epoch:
            return False, "express_commit"
        if o_cache[2] != n_cache[2]:
            return False, "fence_epoch"
        if o_cache[1] != n_cache[1]:
            return False, "generation"
        if o_cache[0] != n_cache[0]:
            return False, "watch_delta"
        if o_cache[5:7] != n_cache[5:7]:
            # job-side belt-and-braces (VT009): an unmarked job mutation
            # moved the status-version sum without touching dirty epoch
            return False, "job_version"
        return False, "acct_gen"

    # -- cycle entry ---------------------------------------------------------

    def run_cycle(self) -> Dict:
        """One COMMITTED session per call (plus, usually, the next
        cycle's speculative dispatch left in flight). Returns the cycle
        info dict (mode, timings, speculation outcome)."""
        t_cycle = time.perf_counter()
        info: Dict[str, object] = {}
        st, self._inflight = self._inflight, None
        try:
            actions, tiers = self.policy_fn()
            names = [a if isinstance(a, str) else a.name() for a in actions]
            if st is not None:
                ok, reason = self._check(st, tiers)
                if ok:
                    pending, st = st, None
                    ssn = self._commit(pending, info)
                    if ssn is None:  # kernel failure at fetch: rerun
                        ssn = self._full_cycle(actions, names, tiers, info)
                else:
                    self._discard(st, reason)
                    st = None
                    self.stats["spec_reruns"] += 1
                    info["spec"] = f"discarded:{reason}"
                    ssn = self._full_cycle(actions, names, tiers, info)
            else:
                ssn = self._full_cycle(actions, names, tiers, info)
            self.stats["committed"] += 1
            if self.intake is not None:
                # quantized delta ingest: arrivals drained here are INSIDE
                # the next snapshot's seal instead of invalidating it
                self.intake()
            # solve-ahead for the NEXT cycle, dispatched before this
            # session's close so the device works through the close-side
            # host writebacks and the inter-cycle window
            self._speculate(actions, names, tiers, info)
            t0 = time.perf_counter()
            close_session(ssn)
            info["close_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        except Exception:
            # a crashed pipelined cycle must not strand a half-dispatched
            # speculation — neither the stage detached at entry nor one
            # this cycle dispatched; the degrade ladder decides how many
            # crashes buy a fallback to the serial loop
            if st is not None:
                self._discard(st, "abandoned")
            self.abandon()
            self.degrade.note_pipeline_error()
            raise
        self.degrade.note_pipeline_ok()
        self.stats["cycles"] += 1
        wall = time.perf_counter() - t_cycle
        info["e2e_ms"] = round(wall * 1e3, 3)
        self._cycle_walls.append(wall)
        if len(self._cycle_walls) > self._RATE_WINDOW:
            del self._cycle_walls[0]
        total = sum(self._cycle_walls)
        if total > 0:
            metrics.set_pipeline_sessions_per_sec(
                round(len(self._cycle_walls) / total, 3))
        return info

    def abandon(self) -> None:
        """Drop any in-flight speculation without applying it (shutdown,
        leadership loss, crashed cycle). The discard counter stays honest
        — an abandoned stage was never applied either."""
        st, self._inflight = self._inflight, None
        if st is not None:
            self._discard(st, "abandoned")

    # -- the non-speculative (serial-order) cycle ---------------------------

    def _chain_ok(self, names: List[str]) -> bool:
        if "allocate" not in names:
            return False
        order = [n for n in _CHAIN if n in names]
        return list(names) == order

    def _preamble(self, ssn) -> None:
        """The run_actions head every COMMITTING session owes: express
        reconciliation (the session is the fairness authority for every
        outstanding optimistic bind) and the takeover recovery sweep."""
        lane = getattr(self.cache, "express_lane", None)
        if lane is not None:
            from volcano_tpu.express.reconcile import reconcile_session

            lane.set_tiers(ssn.tiers)
            reconcile_session(ssn)
        if getattr(self.cache, "fence_sweep_due", False):
            self.cache.fence_sweep_due = False
            takeover_recovery_sweep(ssn)

    def _full_cycle(self, actions, names, tiers, info) -> object:
        """Open + run + (caller closes) one session in strict serial
        order — the re-run path after a discard, and every cycle whose
        chain is outside the pipelined envelope."""
        ssn = open_session(self.cache, tiers)
        if not self._chain_ok(names):
            self.stats["fallback_cycles"] += 1
            info["mode"] = "fallback"
            info["action_ms"] = run_actions(ssn, actions)
            return ssn
        self._preamble(ssn)
        action_ms: Dict[str, float] = {}
        t0 = time.perf_counter()
        if "enqueue" in names:
            get_action("enqueue").execute(ssn)
            action_ms["enqueue"] = round(
                (time.perf_counter() - t0) * 1e3, 3)
        solver = getattr(ssn, "batch_allocator", None)
        prep = solver._prepare(ssn) if solver is not None else None
        t0 = time.perf_counter()
        if prep is None or prep["mode"] != "rounds" \
                or prep["staged"] is None:
            # sub-threshold / fallback sessions: the allocate action owns
            # its own solver ladder (serial oracle included)
            info["mode"] = "per_action"
            for name in names:
                if name == "enqueue":
                    continue
                t1 = time.perf_counter()
                get_action(name).execute(ssn)
                action_ms[name] = round(
                    (time.perf_counter() - t1) * 1e3, 3)
            info["action_ms"] = action_ms
            return ssn
        if self._solve_and_apply(ssn, solver, prep, wait=None):
            from volcano_tpu.scheduler.actions.allocate import \
                finish_batched

            finish_batched(ssn, solver)
        else:
            # dispatch/fetch failure: the allocate action retries through
            # its own fallback ladder (serial host solve), which runs
            # finish_batched itself when the retry lands batched
            get_action("allocate").execute(ssn)
        action_ms["allocate"] = round((time.perf_counter() - t0) * 1e3, 3)
        if "backfill" in names:
            t1 = time.perf_counter()
            get_action("backfill").execute(ssn)
            action_ms["backfill"] = round(
                (time.perf_counter() - t1) * 1e3, 3)
        info.setdefault("mode", "pipelined")
        info["action_ms"] = action_ms
        return ssn

    def _solve_and_apply(self, ssn, solver, prep, wait) -> bool:
        """Dispatch (or, with ``wait`` given, consume the speculative
        fetch) + parse + bulk-apply one packed rounds solve. Returns
        False when the device path failed BEFORE anything was applied."""
        from volcano_tpu.scheduler import degrade as degrade_mod
        from volcano_tpu.utils import devprof

        try:
            if wait is None:
                from volcano_tpu.ops import rounds as rounds_mod

                tp = time.perf_counter()
                wait = devprof.start_fetch(rounds_mod.solve_rounds_packed(
                    prep["spec"], prep["layout"], prep["staged"]))
                out = wait()
                solver.profile["pack_s"] = prep["pack_s"]
                solver.profile["h2d_s"] = prep["h2d_s"]
                solver.profile["dispatch_s"] = time.perf_counter() - tp
            else:
                out = wait()
            assign, meta = solver.parse_packed(out)
        except Exception as e:
            logger.exception("pipeline solve failed; serial fallback")
            solver.profile["fallback"] = f"solve error: {e}"
            degrade_mod.note_kernel_failure()
            return False
        degrade_mod.note_kernel_ok()
        solver.apply_packed(ssn, prep, np.asarray(assign), meta)
        return True

    # -- speculation ---------------------------------------------------------

    def _skip(self, info, reason: str) -> None:
        skips = self.stats["spec_skips"]
        skips[reason] = skips.get(reason, 0) + 1
        info.setdefault("spec", f"skipped:{reason}")

    def _speculate(self, actions, names, tiers, info) -> None:
        """Open the NEXT cycle's session and dispatch its solve before
        the current one closes. Leaves self._inflight set on success;
        otherwise records why this cycle declined to solve ahead."""
        if not self.spec or self.degrade.force_serial():
            self._skip(info, "disabled")
            return
        if not self._chain_ok(names):
            self._skip(info, "chain_shape")
            return
        lane = getattr(self.cache, "express_lane", None)
        if lane is not None and lane.outstanding:
            # outstanding optimistic binds: their reconcile verdicts (and
            # any freed revert capacity) must land BEFORE the solve
            # encodes — the committing session owns them, never this one
            self._skip(info, "express_tokens")
            return
        if getattr(self.cache, "fence_sweep_due", False):
            self._skip(info, "fence_sweep_due")
            return
        ssn = open_session(self.cache, tiers)
        flips = self._staged_enqueue(ssn) if "enqueue" in names else []
        if flips is None:
            self._release(ssn)
            self._skip(info, "enqueue_active")
            return
        # encode with the staged flips APPLIED (the encoder excludes
        # Pending-phase jobs — encoder.py job gate), then park them until
        # commit: the shared PodGroup objects must carry zero observable
        # state while this session is merely speculative
        solver = getattr(ssn, "batch_allocator", None)
        try:
            prep = solver._prepare(ssn) if solver is not None else None
        finally:
            for pg in flips:
                pg.status.phase = objects.PodGroupPhase.PENDING
        if prep is None or prep["mode"] != "rounds" \
                or prep["staged"] is None:
            self._release(ssn)
            self._skip(info, "not_packed_rounds")
            return
        fingerprint = self._fingerprint(tiers)
        try:
            from volcano_tpu.ops import rounds as rounds_mod
            from volcano_tpu.utils import devprof

            t_dispatch = time.perf_counter()
            dev = rounds_mod.solve_rounds_packed(
                prep["spec"], prep["layout"], prep["staged"])
            wait = devprof.start_fetch(dev)
        except Exception:
            logger.exception("speculative dispatch failed; cycle will "
                             "run serially")
            from volcano_tpu.scheduler import degrade as degrade_mod

            degrade_mod.note_kernel_failure()
            self._release(ssn)
            self._skip(info, "dispatch_error")
            return
        self._inflight = _InFlight(ssn, names, prep, dev, wait,
                                   fingerprint, flips, tiers, t_dispatch)
        self.stats["spec_dispatched"] += 1
        info.setdefault("spec", "dispatched")

    def _staged_enqueue(self, ssn):
        """Run the REAL enqueue action and record its Pending->Inqueue
        flips. The flips land on PodGroup objects SHARED with the cache/
        store, so the caller parks them back to Pending after the encode
        and re-applies them only at commit — a discarded speculative
        session must leave zero observable state. Returns the flip list
        still APPLIED (the encode needs the admitted phase), or None when
        a flipped job already has pending tasks — the serial order would
        let allocate see it admitted this cycle, so the cycle must not
        speculate (the caller reverts before declining)."""
        PENDING = objects.PodGroupPhase.PENDING
        before = []
        for job in ssn.jobs.values():
            pg = job.pod_group
            if pg is not None and pg.status.phase == PENDING:
                before.append((job, pg))
        get_action("enqueue").execute(ssn)
        flips = []
        active = False
        for job, pg in before:
            if pg.status.phase == objects.PodGroupPhase.INQUEUE:
                flips.append(pg)
                if job.task_status_index.get(TaskStatus.PENDING):
                    active = True
        if active:
            for pg in flips:
                pg.status.phase = PENDING
            return None
        return flips

    # -- commit / discard ----------------------------------------------------

    def _commit(self, st: _InFlight, info) -> Optional[object]:
        """The fingerprint held: this speculative session IS the cycle.
        Returns the session, or None when the fetch failed (the caller
        re-runs the cycle serially; nothing was applied)."""
        ssn = st.ssn
        solver = ssn.batch_allocator
        t0 = time.perf_counter()
        self._preamble(ssn)  # no outstanding tokens by fingerprint;
        #                      reconcile still bumps the lane's session seq
        for pg in st.flips:
            pg.status.phase = objects.PodGroupPhase.INQUEUE
        # apply-time re-check, the sim auditor's pipeline_no_stale_commit
        # witness: stale_commits counts stages whose fingerprint mismatched
        # HERE, past the cycle-entry check — it must stay 0 (nothing on
        # this thread may move state between the two probes), and if it
        # ever fires the stage is still discarded, never applied
        ok, reason = self._check(st, st.tiers)
        if not ok:
            self.stats["stale_commits"] += 1
            self._note_discard(f"stale_at_apply:{reason}")
            self.stats["spec_reruns"] += 1
            info["spec"] = f"discarded:stale_at_apply:{reason}"
            self._revert_flips(st)
            from volcano_tpu.utils import devprof

            devprof.discard(st.dev)
            self._release(ssn)
            return None
        t_wait = time.perf_counter()
        overlap_s = t_wait - st.t_dispatch
        if not self._solve_and_apply(ssn, solver, st.prep, wait=st.wait):
            # fetch failed: treat exactly like a discard — nothing from
            # this stage was applied — and let the caller re-run
            self._note_discard("kernel_error")
            self.stats["spec_reruns"] += 1
            info["spec"] = "discarded:kernel_error"
            self._revert_flips(st)
            self._release(ssn)
            return None
        from volcano_tpu.scheduler.actions.allocate import finish_batched

        finish_batched(ssn, solver)
        action_ms = {"allocate": round(
            (time.perf_counter() - t0) * 1e3, 3)}
        if "backfill" in st.names:
            t1 = time.perf_counter()
            get_action("backfill").execute(ssn)
            action_ms["backfill"] = round(
                (time.perf_counter() - t1) * 1e3, 3)
        self.stats["spec_applied"] += 1
        metrics.observe_pipeline_overlap(overlap_s)
        info["mode"] = "speculative"
        info["overlap_ms"] = round(overlap_s * 1e3, 3)
        info["spec_applied"] = True
        info["action_ms"] = action_ms
        return ssn

    def _revert_flips(self, st: _InFlight) -> None:
        for pg in st.flips:
            pg.status.phase = objects.PodGroupPhase.PENDING

    def _note_discard(self, reason: str) -> None:
        self.stats["spec_discarded"] += 1
        discards = self.stats["spec_discards"]
        discards[reason] = discards.get(reason, 0) + 1
        metrics.register_pipeline_spec_discard(reason)

    def _discard(self, st: _InFlight, reason: str) -> None:
        """An invalidated speculative stage: never fetched into session
        state, never applied. The device result is dropped untouched and
        the early-opened session is released without close-side effects
        (it made none — enqueue flips were staged-and-reverted and no
        statement ever committed)."""
        from volcano_tpu.utils import devprof

        self._note_discard(reason)
        devprof.discard(st.dev)
        self._release(st.ssn)

    @staticmethod
    def _release(ssn) -> None:
        """Drop a session that never committed anything: clear the same
        references close_session clears, WITHOUT plugin close hooks,
        status writebacks, or the job updater — a speculative session
        that did not commit must be invisible."""
        ssn.jobs = {}
        ssn.nodes = {}
        ssn.node_axis = None
        ssn.plugins = {}
        ssn.event_handlers = []
        ssn.job_order_fns = {}
        ssn.namespace_order_fns = {}
        ssn.queue_order_fns = {}
