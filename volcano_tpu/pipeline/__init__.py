"""volcano_tpu.pipeline — the continuous scheduling pipeline.

Double-buffered sessions with speculative solve-ahead: while cycle N's
results are host-replayed and its close-side writebacks run, cycle N+1's
snapshot is already delta-opened from the SnapshotKeeper's buffer pair
and its device solve is speculatively in flight. A delta fingerprint
sealed at dispatch and re-checked before apply guarantees an invalidated
speculative stage is never applied (docs/DESIGN.md §16).

``VOLCANO_TPU_PIPELINE=0`` keeps the serial loop (the byte-for-byte
oracle); ``VOLCANO_TPU_PIPELINE_SPEC=0`` keeps the pipelined loop but
never speculates (double-buffer-only mode, the parity fuzz's midpoint).
"""

from volcano_tpu.pipeline.driver import PipelineDriver, pipeline_enabled, \
    speculation_enabled

__all__ = ["PipelineDriver", "pipeline_enabled", "speculation_enabled"]
