"""Admission backpressure — the front door's intake gate.

A submission storm today grows the store/controller backlog without
bound: every Job is validated, created, and queued no matter how far
behind the scheduler already is. This module adds the missing policy —
bounded-inflight admission with a token-bucket intake and
priority-aware shedding — as ordinary store admission middleware, so it
guards the in-process path and the HTTP gateway identically:

- ``IntakeGate.admit(priority)`` takes one token from a refilling bucket
  (rate ``rate_per_s``, depth ``burst``) and checks the backlog bound;
  when either is exhausted it raises ``OverloadedError`` carrying a
  computed ``retry_after`` — rejected-WITH-retry, never a silent drop.
- Priority-aware shedding: the last ``interactive_reserve`` fraction of
  both the bucket and the backlog budget is reserved for interactive /
  express-eligible arrivals (``classify_job``: the express envelope's
  shape — small task count, tiny gang), so under a burst the batch
  storm sheds FIRST and interactive latency degrades LAST.
- ``set_backlog`` feeds the demand signal (pending pods / gated
  PodGroups, published per scheduler cycle) — admission slows down when
  the scheduler is behind, which is what turns an unbounded-queue storm
  into bounded latency.

Every shed notifies the degradation ladder (``admission_shed`` rung) and
meters ``volcano_admission_shed_total{reason}`` plus the
``volcano_admission_retry_after_seconds`` histogram. Time comes from
utils/clock.now() — the simulator's virtual clock during a sim run — so
shedding decisions replay byte-identically under the same seed.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from volcano_tpu.store.store import OverloadedError, Store
from volcano_tpu.utils import clock

PRIORITY_CLASSES = ("interactive", "batch")


def classify_job(job) -> str:
    """"interactive" when the job fits the express lane's eligibility
    envelope (small task count, tiny/no gang — the latency-sensitive
    class), else "batch". Interactive arrivals shed LAST."""
    try:
        from volcano_tpu.express.trigger import (
            EXPRESS_MAX_GANG, EXPRESS_MAX_TASKS)
    except Exception:  # express package absent/ungated embedders
        EXPRESS_MAX_TASKS, EXPRESS_MAX_GANG = 8, 4
    try:
        replicas = sum(int(t.replicas) for t in job.spec.tasks)
        min_avail = int(job.spec.min_available)
    except Exception:
        return "batch"
    if replicas <= EXPRESS_MAX_TASKS and min_avail <= EXPRESS_MAX_GANG:
        return "interactive"
    return "batch"


class IntakeGate:
    """Token-bucket + backlog-bound admission with an interactive
    reserve. Thread-safe; deterministic under utils/clock."""

    def __init__(self, rate_per_s: float = 200.0,
                 burst: Optional[float] = None,
                 max_backlog: int = 0,
                 interactive_reserve: float = 0.25,
                 backlog_retry_s: float = 2.0,
                 ladder=None):
        if rate_per_s <= 0:
            raise ValueError("intake needs rate_per_s > 0")
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst is not None \
            else max(2.0 * self.rate, 2.0)
        self.max_backlog = int(max_backlog)
        self.interactive_reserve = min(max(float(interactive_reserve),
                                           0.0), 0.9)
        self.backlog_retry_s = float(backlog_retry_s)
        self._explicit_ladder = ladder
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._stamp: Optional[float] = None
        self._backlog = 0
        self.counters: Dict[str, float] = {
            "admitted": 0, "admitted_interactive": 0, "admitted_batch": 0,
            "shed_total": 0, "shed_rate": 0, "shed_backlog": 0,
            "shed_interactive": 0, "shed_batch": 0,
            "retry_after_s_sum": 0.0}

    def _ladder(self):
        if self._explicit_ladder is not None:
            return self._explicit_ladder
        from volcano_tpu.scheduler import degrade

        return degrade.default_ladder()

    # -- signals ------------------------------------------------------------

    def set_backlog(self, n: int) -> None:
        """Feed the demand signal (pending work the scheduler has not
        yet placed) — published once per cycle by the scheduler loop or
        the sim harness."""
        with self._lock:
            self._backlog = max(int(n), 0)

    # -- the gate -----------------------------------------------------------

    def _refill(self, now: float) -> None:
        if self._stamp is None:
            self._stamp = now
            return
        elapsed = max(now - self._stamp, 0.0)
        if elapsed > 0:
            self._tokens = min(self.burst,
                               self._tokens + elapsed * self.rate)
            self._stamp = now

    def admit(self, priority: str = "batch", cost: float = 1.0) -> None:
        """Admit one submission or raise OverloadedError(retry_after).

        Shedding order is priority-aware on BOTH axes: batch arrivals
        cannot spend the last ``interactive_reserve`` fraction of the
        bucket, and they shed at ``(1 - reserve) * max_backlog`` while
        interactive arrivals ride to the full bound."""
        interactive = priority == "interactive"
        with self._lock:
            now = clock.now()
            self._refill(now)
            if self.max_backlog > 0:
                limit = self.max_backlog if interactive else int(
                    self.max_backlog * (1.0 - self.interactive_reserve))
                if self._backlog >= max(limit, 1):
                    retry = self.backlog_retry_s
                    self._note_shed("backlog", priority, retry)
                    raise OverloadedError(
                        f"intake backlog {self._backlog} >= {limit} for "
                        f"{priority}; retry in {retry:.3f}s",
                        retry_after=retry, reason="backlog")
            floor = 0.0 if interactive \
                else self.burst * self.interactive_reserve
            if self._tokens - cost < floor:
                need = floor + cost - self._tokens
                retry = max(need / self.rate, 1e-3)
                self._note_shed("rate", priority, retry)
                raise OverloadedError(
                    f"intake rate exhausted for {priority} "
                    f"(tokens={self._tokens:.2f}, floor={floor:.2f}); "
                    f"retry in {retry:.3f}s",
                    retry_after=retry, reason="rate")
            self._tokens -= cost
            self.counters["admitted"] += 1
            self.counters[f"admitted_{priority}"] = \
                self.counters.get(f"admitted_{priority}", 0) + 1
        try:
            self._ladder().note_admission_ok()
        except Exception:
            pass

    def _note_shed(self, reason: str, priority: str,
                   retry_after: float) -> None:
        self.counters["shed_total"] += 1
        self.counters[f"shed_{reason}"] += 1
        self.counters[f"shed_{priority}"] = \
            self.counters.get(f"shed_{priority}", 0) + 1
        self.counters["retry_after_s_sum"] += retry_after
        try:
            from volcano_tpu.scheduler import metrics

            metrics.register_admission_shed(reason)
            metrics.observe_admission_retry_after(retry_after)
        except Exception:
            pass
        try:
            self._ladder().note_admission_shed()
        except Exception:
            pass

    # -- stats --------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self.counters)
            out["tokens"] = round(self._tokens, 3)
            out["backlog"] = self._backlog
            out["rate_per_s"] = self.rate
            out["burst"] = self.burst
            out["max_backlog"] = self.max_backlog
            attempts = out["admitted"] + out["shed_total"]
            out["attempts"] = attempts
            out["shed_fraction"] = round(
                out["shed_total"] / attempts, 4) if attempts else 0.0
            return out


def install_intake(store: Store, gate: IntakeGate,
                   kinds=("Job",)) -> IntakeGate:
    """Register the gate as admission middleware. It runs BEHIND the
    functional validators (admission/admission.py registers first), so a
    malformed submission is rejected 422 without consuming intake budget
    — only well-formed load competes for tokens."""
    for kind in kinds:
        if kind == "Job":
            store.register_admission(
                kind, validator=lambda job: gate.admit(classify_job(job)))
        else:
            store.register_admission(
                kind, validator=lambda obj: gate.admit("batch"))
    return gate
