"""Admission: job validation/mutation + the delay-pod-creation gate
(volcano pkg/admission/)."""

from volcano_tpu.admission.admission import (
    install,
    mutate_job,
    validate_job,
    validate_pod,
)
from volcano_tpu.admission.intake import (
    IntakeGate,
    classify_job,
    install_intake,
)

__all__ = ["install", "mutate_job", "validate_job", "validate_pod",
           "IntakeGate", "classify_job", "install_intake"]
