"""Admission: job validation/mutation + the delay-pod-creation gate
(volcano pkg/admission/)."""

from volcano_tpu.admission.admission import (
    install,
    mutate_job,
    validate_job,
    validate_pod,
)

__all__ = ["install", "mutate_job", "validate_job", "validate_pod"]
