"""Admission middleware on the store — the webhook analog
(volcano pkg/admission/{admission_controller,admit_job,mutate_job,admit_pod}.go).

``install(store)`` registers:
- Job mutator: default queue + default task names (mutate_job.go:77-116);
- Job validator: the full validation matrix (admit_job.go:77-202);
- Pod validator: the delay-pod-creation gate — pods of a Pending PodGroup
  are rejected until the scheduler's enqueue action flips it to Inqueue
  (admit_pod.go:94-143, docs/design/delay-pod-creation.md).
"""

from __future__ import annotations

import re
from typing import List, Optional

from volcano_tpu.api import objects
from volcano_tpu.api.objects import JobAction, JobEvent
from volcano_tpu.store.store import AdmissionError, Store

DEFAULT_QUEUE = "default"
DEFAULT_TASK_SPEC = "task"

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

# allow-maps: internal events/actions rejected (admission_controller.go:117-139)
VALID_POLICY_EVENTS = {
    JobEvent.ANY, JobEvent.POD_FAILED, JobEvent.POD_EVICTED, JobEvent.JOB_UNKNOWN,
    JobEvent.TASK_COMPLETED,
}
VALID_POLICY_ACTIONS = {
    JobAction.ABORT_JOB, JobAction.RESTART_JOB, JobAction.RESTART_TASK,
    JobAction.TERMINATE_JOB, JobAction.COMPLETE_JOB, JobAction.RESUME_JOB,
}


def is_dns1123_label(name: str) -> bool:
    return len(name) <= 63 and bool(_DNS1123.match(name))


def validate_policies(policies: List[objects.LifecyclePolicy]) -> str:
    """(admission_controller.go:123-180)"""
    seen_events = set()
    seen_exit_codes = set()
    for policy in policies:
        has_event = bool(policy.event or policy.events)
        if has_event and policy.exit_code is not None:
            return "must not specify event and exitCode simultaneously;"
        if not has_event and policy.exit_code is None:
            return "either event and exitCode should be specified;"
        if has_event:
            events = list(policy.events)
            if policy.event:
                events.append(policy.event)
            for event in events:
                if event not in VALID_POLICY_EVENTS:
                    return f"invalid policy event: {event};"
                if policy.action not in VALID_POLICY_ACTIONS:
                    return f"invalid policy action: {policy.action};"
                if event in seen_events:
                    return f"duplicate event {event} across different policy;"
                seen_events.add(event)
        else:
            if policy.exit_code == 0:
                return "0 is not a valid error code;"
            if policy.exit_code in seen_exit_codes:
                return f"duplicate exitCode {policy.exit_code};"
            seen_exit_codes.add(policy.exit_code)
    return ""


def validate_job(store: Optional[Store], job: objects.Job) -> None:
    """Raises AdmissionError on the first/accumulated violations
    (admit_job.go:77-167)."""
    if job.spec.min_available <= 0:
        raise AdmissionError("'minAvailable' must be greater than zero.")
    if job.spec.max_retry < 0:
        raise AdmissionError("'maxRetry' cannot be less than zero.")
    if (job.spec.ttl_seconds_after_finished is not None
            and job.spec.ttl_seconds_after_finished < 0):
        raise AdmissionError("'ttlSecondsAfterFinished' cannot be less than zero.")
    if not job.spec.tasks:
        raise AdmissionError("No task specified in job spec")

    msg = ""
    task_names = set()
    total_replicas = 0
    for task in job.spec.tasks:
        if task.replicas <= 0:
            msg += f" 'replicas' is not set positive in task: {task.name};"
        total_replicas += task.replicas
        if not is_dns1123_label(task.name):
            msg += (f" task name {task.name!r} must be a lowercase RFC 1123 "
                    f"label;")
        if task.name in task_names:
            msg += f" duplicated task name {task.name};"
            break
        task_names.add(task.name)
        msg += validate_policies(task.policies)
        if not task.template.spec.containers:
            msg += f" task {task.name} has no containers;"

    if total_replicas < job.spec.min_available:
        msg += " 'minAvailable' should not be greater than total replicas in tasks;"

    msg += validate_policies(job.spec.policies)

    from volcano_tpu.controllers.job import plugins as job_plugins

    for name in job.spec.plugins:
        if job_plugins.get_plugin_builder(name) is None:
            msg += f" unable to find job plugin: {name}"

    if store is not None and job.spec.queue:
        if store.try_get("Queue", "", job.spec.queue) is None:
            msg += f" unable to find job queue: {job.spec.queue}"

    if msg:
        raise AdmissionError(msg.strip())


def mutate_job(job: objects.Job) -> None:
    """Default queue + default task names (mutate_job.go:77-116)."""
    if not job.spec.queue:
        job.spec.queue = DEFAULT_QUEUE
    for index, task in enumerate(job.spec.tasks):
        if not task.name:
            task.name = f"{DEFAULT_TASK_SPEC}{index}"


def validate_pod(store: Store, pod: objects.Pod,
                 scheduler_name: str = "volcano") -> None:
    """The delay-pod-creation gate (admit_pod.go:94-143)."""
    if pod.spec.scheduler_name != scheduler_name:
        return
    pg_name = pod.metadata.annotations.get(objects.GROUP_NAME_ANNOTATION_KEY, "")
    if pg_name:
        pg = store.try_get("PodGroup", pod.metadata.namespace, pg_name)
        if pg is None:
            raise AdmissionError(
                f"Failed to get PodGroup for pod "
                f"<{pod.metadata.namespace}/{pod.metadata.name}>: not found")
        if pg.status.phase == objects.PodGroupPhase.PENDING:
            raise AdmissionError(
                f"Failed to create pod <{pod.metadata.namespace}/"
                f"{pod.metadata.name}>, because the podgroup phase is Pending")
        return
    # normal pod: gate only if its auto-created podgroup exists and is Pending
    pg = store.try_get("PodGroup", pod.metadata.namespace,
                       f"podgroup-{pod.metadata.uid}")
    if pg is not None and pg.status.phase == objects.PodGroupPhase.PENDING:
        raise AdmissionError(
            f"Failed to create pod <{pod.metadata.namespace}/"
            f"{pod.metadata.name}>, because the podgroup phase is Pending")


def install(store: Store, scheduler_name: str = "volcano",
            gate_pods: bool = True) -> None:
    """Register the webhook analogs as store admission middleware."""
    store.register_admission(
        "Job",
        mutator=lambda job: mutate_job(job),
        validator=lambda job: validate_job(store, job),
    )
    if gate_pods:
        store.register_admission(
            "Pod",
            validator=lambda pod: validate_pod(store, pod, scheduler_name),
        )
