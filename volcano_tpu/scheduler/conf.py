"""Scheduler configuration schema
(volcano pkg/scheduler/conf/scheduler_conf.go:19-58).

YAML shape:

.. code-block:: yaml

    actions: "enqueue, allocate, backfill"
    tiers:
    - plugins:
      - name: priority
      - name: gang
    - plugins:
      - name: drf
      - name: predicates
        arguments:
          predicate.MemoryPressureEnable: "true"
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class PluginOption:
    """One plugin entry in a tier with its 10 enable flags (None = unset,
    defaulted to True by apply_plugin_conf_defaults, plugins/defaults.go:24)."""

    name: str
    enabled_job_order: Optional[bool] = None
    enabled_namespace_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    arguments: Dict[str, str] = field(default_factory=dict)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConfiguration:
    actions: str = ""
    tiers: List[Tier] = field(default_factory=list)


def enabled(flag: Optional[bool]) -> bool:
    """Tri-state flag check (session_plugins.go isEnabled): only an explicit
    True (post-defaulting) enables the extension point."""
    return flag is True


_ENABLE_FLAGS = (
    "enabled_job_order",
    "enabled_namespace_order",
    "enabled_job_ready",
    "enabled_job_pipelined",
    "enabled_task_order",
    "enabled_preemptable",
    "enabled_reclaimable",
    "enabled_queue_order",
    "enabled_predicate",
    "enabled_node_order",
)


def apply_plugin_conf_defaults(option: PluginOption) -> None:
    """All 10 enable flags default True when unset
    (plugins/defaults.go:24)."""
    for flag in _ENABLE_FLAGS:
        if getattr(option, flag) is None:
            setattr(option, flag, True)
