"""Scheduler driver — the periodic session loop
(volcano pkg/scheduler/scheduler.go + util.go).

Every cycle: reload the policy YAML (hot-reload semantics, scheduler.go:77),
open a session over the cache snapshot, run the configured actions in order,
close the session (status writeback). The conf schema matches
conf/scheduler_conf.go:19-58; the default conf is the reference's
(util.go:31-42) — the tpuscore gate is added via conf, not hardcoded.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

import yaml

from volcano_tpu.scheduler import conf, degrade as degrade_mod, metrics
from volcano_tpu.scheduler import plugins as _plugins  # noqa: F401 (register)
from volcano_tpu.scheduler import actions as _actions  # noqa: F401 (register)
from volcano_tpu.scheduler.framework import (
    close_session,
    get_action,
    open_session,
    run_actions,
)

logger = logging.getLogger(__name__)

DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# The TPU-gated variant: identical policy tiers plus the tpuscore batch gate.
TPU_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: tpuscore
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

_FLAG_KEYS = {
    "enableJobOrder": "enabled_job_order",
    "enableNamespaceOrder": "enabled_namespace_order",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableNodeOrder": "enabled_node_order",
}


def _parse_bool(v) -> bool:
    """Quoted YAML booleans ('false') must not read as truthy strings."""
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "t", "true", "yes")


def load_scheduler_conf(conf_str: str) -> Tuple[List, List[conf.Tier]]:
    """YAML -> ([Action], [Tier]) with per-plugin flag defaulting
    (util.go:44-72)."""
    data = yaml.safe_load(conf_str) or {}
    tiers: List[conf.Tier] = []
    for tier_data in data.get("tiers", []) or []:
        options = []
        for p in tier_data.get("plugins", []) or []:
            option = conf.PluginOption(name=p["name"])
            for yaml_key, attr in _FLAG_KEYS.items():
                if yaml_key in p:
                    setattr(option, attr, _parse_bool(p[yaml_key]))
            args = p.get("arguments") or {}
            option.arguments = {str(k): str(v) for k, v in args.items()}
            conf.apply_plugin_conf_defaults(option)
            options.append(option)
        tiers.append(conf.Tier(plugins=options))

    actions = []
    for name in str(data.get("actions", "")).split(","):
        name = name.strip()
        if not name:
            continue
        actions.append(get_action(name))  # raises KeyError like util.go errors
    return actions, tiers


def read_scheduler_conf(path: str) -> str:
    with open(path) as f:
        return f.read()


class Scheduler:
    """Periodic scheduler (scheduler.go:34-106)."""

    def __init__(
        self,
        cache,
        scheduler_conf: str = "",
        schedule_period: float = 1.0,
        conf_path: Optional[str] = None,
        mesh=None,
        express: bool = False,
        pipeline: bool = False,
    ):
        self.cache = cache
        self.scheduler_conf = scheduler_conf or DEFAULT_SCHEDULER_CONF
        self.conf_path = conf_path
        self.schedule_period = schedule_period
        if mesh is not None:
            from volcano_tpu.scheduler.plugins import tpuscore

            tpuscore.set_default_mesh(mesh)
        self.actions: List = []
        self.tiers: List[conf.Tier] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # express lane (volcano_tpu/express): event-driven sub-10 ms
        # placement of small interactive arrivals BETWEEN periodic
        # sessions; the loop services the lane's wake event during the
        # inter-cycle wait, and every full session reconciles
        self.express_lane = None
        self._express = express
        # continuous pipeline (volcano_tpu/pipeline): double-buffered
        # sessions with speculative solve-ahead — the sustained-throughput
        # loop. VOLCANO_TPU_PIPELINE=0 keeps the serial run_once cycle
        # (the byte-for-byte oracle) regardless of this flag, and the
        # degrade ladder's pipeline_disabled rung falls back to it live.
        self._pipeline = pipeline
        self.pipeline_driver = None
        # conf-parse cache: the pipeline's speculation fingerprint keys on
        # the tiers OBJECT identity, so an unchanged conf text must hand
        # back the same parsed objects cycle over cycle
        self._conf_cache: Optional[Tuple[str, List, List[conf.Tier]]] = None
        # fault-degradation policy (scheduler/degrade.py): the process
        # default so the solver's kernel-failure hooks and this loop's
        # session gate share one ladder; embedders report remote-store
        # health through it too
        self.degrade = degrade_mod.default_ladder()

    # -- lifecycle ---------------------------------------------------------

    def set_fence_epoch(self, epoch) -> None:
        """Stamp the effector write-path with the leadership epoch the
        elector just acquired (scheduler/leaderelection.py epoch();
        store/store.py FencedError). Call BEFORE run() on each
        acquisition so no session of the new term writes unfenced."""
        self.cache.set_fence_epoch(epoch)

    def run(self) -> None:
        """Start cache sync then the periodic loop in a background thread
        (scheduler.go:63-69). Restartable: a leader elector may stop the
        loop on lost leadership and run it again on re-election."""
        self.cache.run()
        self.cache.wait_for_cache_sync()
        if self._express and self.express_lane is None:
            try:
                from volcano_tpu.express import ExpressLane

                self.express_lane = ExpressLane(self.cache)
            except Exception:  # pragma: no cover - jax-free host
                logger.exception(
                    "express lane unavailable; arrivals wait for sessions")
                self._express = False
        if self.express_lane is not None:
            # re-acquired leadership (or plain restart): the lane resumes
            # from wherever the last term parked it
            self.express_lane.unpark()
        if self._pipeline and self.pipeline_driver is None:
            try:
                from volcano_tpu.pipeline import (
                    PipelineDriver, pipeline_enabled)

                if pipeline_enabled():
                    self.pipeline_driver = PipelineDriver(
                        self.cache, self._cycle_policy,
                        degrade=self.degrade)
            except Exception:  # pragma: no cover - jax-free host
                logger.exception(
                    "pipeline unavailable; running the serial loop")
                self._pipeline = False
        # fresh Event per generation: if stop()'s bounded join left a
        # previous loop thread mid-run_once, that zombie still sees ITS
        # (set) event and exits; clearing a shared event would revive it
        # alongside the new thread — two loops binding against one cache
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(self._stop,), daemon=True)
        self._thread.start()

    def stop(self, stop_cache: bool = True) -> None:
        if self.pipeline_driver is not None:
            # a stopping (possibly deposed) scheduler must not leave a
            # speculative solve pending — its result is discarded, never
            # applied; a successor term starts from the store's truth
            self.pipeline_driver.abandon()
        if self.express_lane is not None:
            # failover hygiene: a stopping (possibly deposed) scheduler
            # must not keep optimistically binding between sessions; the
            # lane's outstanding tokens survive for the successor's first
            # session to reconcile
            self.express_lane.park("scheduler_stopped")
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if stop_cache and hasattr(self.cache, "stop"):
            self.cache.stop()

    def _loop(self, stop: threading.Event) -> None:
        from volcano_tpu.utils.gcpolicy import LowLatencyGC

        # automatic cyclic GC off while the loop runs: a full-heap scan
        # landing inside a session costs more than the session (gcpolicy.py);
        # young-gen collections run between cycles instead
        policy = LowLatencyGC.install()
        try:
            while not stop.is_set():
                start = time.perf_counter()
                if self.degrade.should_skip_session():
                    # remote-store breaker open (session_skip rung):
                    # scheduling against an unreachable truth would bind
                    # on fantasy state — skip, bounded by the ladder's
                    # staleness budget, until the half-open probe passes
                    logger.warning(
                        "session skipped: store circuit open (%s)",
                        self.degrade.stats()["breakers"]["store"])
                    self._inter_cycle_wait(stop, self.schedule_period)
                    continue
                try:
                    if self.pipeline_driver is not None \
                            and self.degrade.pipeline_allowed():
                        self.run_once_pipelined()
                    else:
                        self.run_once()
                    self.degrade.note_store_ok()
                except Exception as e:
                    from volcano_tpu.store.remote import RemoteStoreError

                    if isinstance(e, RemoteStoreError):
                        self.degrade.note_store_error()
                    logger.exception("scheduling cycle failed")
                policy.maintain()
                elapsed = time.perf_counter() - start
                self._inter_cycle_wait(
                    stop, max(self.schedule_period - elapsed, 0.0))
        finally:
            policy.uninstall()

    def _inter_cycle_wait(self, stop: threading.Event, budget: float) -> None:
        """Sleep until the next periodic session, servicing the express
        lane whenever its wake event fires: an eligible interactive
        arrival places within milliseconds instead of waiting out the
        period. Without a lane this is exactly the old stop.wait()."""
        lane = self.express_lane
        if lane is None:
            stop.wait(budget)
            return
        deadline = time.perf_counter() + budget
        while not stop.is_set():
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return
            # bounded slices keep stop() responsive while the lane idles
            if lane.wake.wait(timeout=min(remaining, 0.05)):
                if stop.is_set():
                    return
                try:
                    lane.run_once()
                except Exception:
                    logger.exception("express run failed")

    # -- one cycle ---------------------------------------------------------

    def load_conf(self) -> None:
        """Hot-reload the policy conf every cycle (scheduler.go:89-106).
        A transiently unreadable file falls back to the configured conf; a
        conf that fails to PARSE keeps the last good actions/tiers so a
        config typo degrades to a logged warning, not a scheduling outage."""
        conf_str = self.scheduler_conf
        if self.conf_path:
            try:
                conf_str = read_scheduler_conf(self.conf_path)
            except OSError as e:
                logger.error(
                    "failed to read scheduler conf %s, using configured "
                    "default: %s", self.conf_path, e)
        cached = self._conf_cache
        if cached is not None and cached[0] == conf_str:
            # unchanged text: reuse the parsed objects — semantics are
            # identical (the parse is deterministic) and the pipeline's
            # speculation fingerprint needs stable tiers identity
            self.actions, self.tiers = cached[1], cached[2]
            return
        try:
            self.actions, self.tiers = load_scheduler_conf(conf_str)
            self._conf_cache = (conf_str, self.actions, self.tiers)
        except Exception as e:
            if self.actions:
                logger.error(
                    "invalid scheduler conf, keeping previous policy: %s", e)
            else:
                logger.error(
                    "invalid scheduler conf and no previous policy; "
                    "using default: %s", e)
                self.actions, self.tiers = load_scheduler_conf(
                    DEFAULT_SCHEDULER_CONF)

    def _cycle_policy(self):
        """PipelineDriver's per-cycle policy source: hot-reloads the conf
        (cached on unchanged text so the tiers object — and therefore the
        speculation fingerprint — is stable across steady-state cycles)."""
        self.load_conf()
        return self.actions, self.tiers

    def run_once_pipelined(self) -> None:
        """One pipelined cycle: commit (or discard+re-run) the in-flight
        speculative session and leave the next cycle's solve dispatched.
        The serial run_once stays byte-for-byte available behind
        VOLCANO_TPU_PIPELINE=0 and the pipeline_disabled degrade rung."""
        start = time.perf_counter()
        info = self.pipeline_driver.run_cycle()
        for name, ms in (info.get("action_ms") or {}).items():
            metrics.update_action_duration(name, ms / 1e3)
        metrics.update_e2e_duration(time.perf_counter() - start)

    def run_once(self) -> None:
        start = time.perf_counter()
        self.load_conf()

        ssn = open_session(self.cache, self.tiers)
        try:
            # fused whole-session dispatch when the session qualifies
            # (ops/session_fuse.py), per-action loop otherwise
            action_ms = run_actions(ssn, self.actions)
            for name, ms in action_ms.items():
                metrics.update_action_duration(name, ms / 1e3)
        finally:
            close_session(ssn)
        metrics.update_e2e_duration(time.perf_counter() - start)
