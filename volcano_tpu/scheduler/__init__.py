"""Scheduler: cache, framework (session/plugins/statement), actions,
policy plugins, metrics, conf, and the periodic driver."""
