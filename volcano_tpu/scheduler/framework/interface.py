"""Plugin and Action interfaces (volcano pkg/scheduler/framework/interface.go)."""

from __future__ import annotations

import abc


class Plugin(abc.ABC):
    """Policy plugin: contributes closures to the session's extension points
    during on_session_open."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def on_session_open(self, ssn) -> None: ...

    def on_session_close(self, ssn) -> None:
        pass


class Action(abc.ABC):
    """Scheduling algorithm, run in configured order each session."""

    @abc.abstractmethod
    def name(self) -> str: ...

    def initialize(self) -> None:
        pass

    @abc.abstractmethod
    def execute(self, ssn) -> None: ...

    def un_initialize(self) -> None:
        pass
