"""Plugin argument map with typed getters
(volcano pkg/scheduler/framework/arguments.go:27-66)."""

from __future__ import annotations

from typing import Dict, Optional


class Arguments(dict):
    """str->str map; getters leave the default unchanged on missing/bad keys."""

    def get_int(self, key: str, default: int) -> int:
        v = self.get(key)
        if v is None or v == "":
            return default
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    def get_bool(self, key: str, default: bool) -> bool:
        v = self.get(key)
        if v is None or v == "":
            return default
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("1", "t", "true", "yes")

    def get_float(self, key: str, default: float) -> float:
        v = self.get(key)
        if v is None or v == "":
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            return default
