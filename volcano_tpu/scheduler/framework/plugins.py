"""Global plugin-builder and action registries
(volcano pkg/scheduler/framework/plugins.go:30-72)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_plugin_builders: Dict[str, Callable] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: Callable) -> None:
    """builder(arguments: Arguments) -> Plugin"""
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[Callable]:
    with _lock:
        return _plugin_builders.get(name)


def register_action(action) -> None:
    with _lock:
        _actions[action.name()] = action


def get_action(name: str):
    with _lock:
        action = _actions.get(name)
    if action is None:
        raise KeyError(f"action {name} is not found")
    return action
