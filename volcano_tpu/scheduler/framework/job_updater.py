"""PodGroup status writeback at session close
(volcano pkg/scheduler/framework/job_updater.go).

The reference parallelizes over 16 workers; here updates are serial and
deterministic (writeback is store-local, not an RPC)."""

from __future__ import annotations

import random
import time

from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import session as session_mod

JOB_CONDITION_UPDATE_TIME = 60.0  # seconds
JOB_CONDITION_UPDATE_JITTER = 30.0


def time_jitter_after(new: float, old: float, duration: float, max_jitter: float) -> bool:
    jitter = random.uniform(0, max_jitter) if max_jitter > 0 else 0.0
    return new > old + duration + jitter


def _conditions_updated(new_conds, old_conds) -> bool:
    """(job_updater.go:57-88): fresh-enough or materially different."""
    if len(new_conds) != len(old_conds):
        return True
    for new_c, old_c in zip(new_conds, old_conds):
        if time_jitter_after(
            new_c.last_transition_time,
            old_c.last_transition_time,
            JOB_CONDITION_UPDATE_TIME,
            JOB_CONDITION_UPDATE_JITTER,
        ):
            return True
        # compare ignoring transition time/ID
        if (
            new_c.type != old_c.type
            or new_c.status != old_c.status
            or new_c.reason != old_c.reason
            or new_c.message != old_c.message
        ):
            return True
    return False


def is_pod_group_status_updated(new: objects.PodGroupStatus, old: objects.PodGroupStatus) -> bool:
    if (
        new.phase != old.phase
        or new.running != old.running
        or new.succeeded != old.succeeded
        or new.failed != old.failed
    ):
        return True
    return _conditions_updated(new.conditions, old.conditions)


class JobUpdater:
    def __init__(self, ssn):
        self.ssn = ssn
        self.job_queue = list(ssn.jobs.values())

    def update_all(self) -> None:
        for job in self.job_queue:
            self._update_job(job)

    def _update_job(self, job) -> None:
        ssn = self.ssn
        if job.pod_group is None:
            ssn.cache.record_job_status_event(job)
            return
        # job_status clones the whole PodGroupStatus to rewrite 4 fields;
        # when the computed values already equal the live status (the
        # common case for jobs a session didn't touch), the clone+assign
        # is value-neutral — keep the current object and skip it.
        # job_status itself never modifies conditions, so field equality
        # IS value equality here.
        cur = job.pod_group.status
        phase, running, failed, succeeded = session_mod.job_status_values(
            ssn, job)
        if (phase == cur.phase and running == cur.running
                and failed == cur.failed and succeeded == cur.succeeded):
            new_status = cur
        else:
            new_status = cur.clone()
            new_status.phase = phase
            new_status.running = running
            new_status.failed = failed
            new_status.succeeded = succeeded
            job.pod_group.status = new_status
        old_status = ssn.pod_group_status.get(job.uid)
        update_pg = old_status is None or is_pod_group_status_updated(
            new_status, old_status
        )
        ssn.cache.update_job_status(job, update_pg)
