"""Session — per-cycle snapshot + plugin extension points + mutation API
(volcano pkg/scheduler/framework/{session.go,session_plugins.go}).

Tiered dispatch semantics (session_plugins.go:106-523), preserved exactly:
- victim fns (preemptable/reclaimable): INTERSECTION within a tier; the first
  tier that produces a non-None result decides;
- order fns (job/queue/task/namespace): first non-zero comparison across
  tiers wins; creation-timestamp+UID tie-break as default;
- job_ready/job_pipelined: AND across all enabled plugins;
- overused: OR;
- job_valid/job_enqueueable: first failure rejects;
- node order: SUM of scores across plugins; batch node order sums per-node.
"""

from __future__ import annotations

import uuid
from typing import Callable, Dict, List, Optional

from volcano_tpu.api import objects
from volcano_tpu.api.cluster_info import ClusterInfo
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.scheduler import conf
from volcano_tpu.scheduler.framework.event_handlers import Event, EventHandler


class Session:
    def __init__(self, cache):
        self.uid = str(uuid.uuid4())
        self.cache = cache

        self.pod_group_status: Dict[str, objects.PodGroupStatus] = {}

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.node_axis = None  # snapshot columnar node capture (nodeaxis.py)
        self.namespace_info: Dict[str, object] = {}

        self.tiers: List[conf.Tier] = []
        self.plugins: Dict[str, object] = {}

        self.event_handlers: List[EventHandler] = []
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        self.task_order_keys: Dict[str, Callable] = {}
        self.namespace_order_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, Callable] = {}
        self.batch_node_order_fns: Dict[str, Callable] = {}
        self.node_map_fns: Dict[str, Callable] = {}
        self.node_reduce_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.job_enqueueable_fns: Dict[str, Callable] = {}

        self._tier_fns_cache: Dict[tuple, List[List[Callable]]] = {}
        self._flat_fns_cache: Dict[tuple, List[Callable]] = {}
        self._stock_task_key_memo = None
        self._node_order_pairs_cache = None
        self._fast_trans = False  # False = not built yet (None = unavailable)
        self._job_valid_memo = None  # None = gate undecided; False = off
        # bumped by every placement-shaped node mutation (allocate/pipeline
        # and their unwinds, plus the bulk writeback). The shared dense
        # preempt view validates against it: a view that missed a mutation
        # rebuilds instead of serving stale used/pod-count state
        self._placement_gen = 0

    # ------------------------------------------------------------------
    # registration (session_plugins.go:26-104)
    # ------------------------------------------------------------------

    def add_job_order_fn(self, name: str, fn) -> None:
        """fn(l_job, r_job) -> int (-1/0/1)"""
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name: str, fn) -> None:
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name: str, fn, key=None) -> None:
        """fn(l_task, r_task) -> int comparator; ``key`` optionally
        registers an equivalent sort KEY (key(task) -> tuple ordering
        ascending exactly as the comparator orders) — when every enabled
        task-order plugin provides one, hot loops use one C-level key sort
        instead of a comparator heap (see stock_task_order_key)."""
        self.task_order_fns[name] = fn
        if key is not None:
            self.task_order_keys[name] = key

    def add_namespace_order_fn(self, name: str, fn) -> None:
        self.namespace_order_fns[name] = fn

    def add_preemptable_fn(self, name: str, fn) -> None:
        """fn(preemptor: TaskInfo, preemptees: [TaskInfo]) -> [TaskInfo]"""
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name: str, fn) -> None:
        self.reclaimable_fns[name] = fn

    def add_job_ready_fn(self, name: str, fn) -> None:
        """fn(job) -> bool"""
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name: str, fn) -> None:
        self.job_pipelined_fns[name] = fn

    def add_predicate_fn(self, name: str, fn) -> None:
        """fn(task, node) -> None, raising FitFailure on mismatch"""
        self.predicate_fns[name] = fn

    def add_node_order_fn(self, name: str, fn) -> None:
        """fn(task, node) -> float"""
        self.node_order_fns[name] = fn

    def add_batch_node_order_fn(self, name: str, fn) -> None:
        """fn(task, nodes) -> {node_name: float}"""
        self.batch_node_order_fns[name] = fn

    def add_node_map_fn(self, name: str, fn) -> None:
        self.node_map_fns[name] = fn

    def add_node_reduce_fn(self, name: str, fn) -> None:
        self.node_reduce_fns[name] = fn

    def add_overused_fn(self, name: str, fn) -> None:
        self.overused_fns[name] = fn

    def add_job_valid_fn(self, name: str, fn) -> None:
        """fn(job) -> Optional[ValidateResult]"""
        self.job_valid_fns[name] = fn

    def add_job_enqueueable_fn(self, name: str, fn) -> None:
        self.job_enqueueable_fns[name] = fn

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # ------------------------------------------------------------------
    # tiered dispatch
    # ------------------------------------------------------------------

    def _tier_plugins(self, flag_name: Optional[str], fns: Dict[str, Callable]):
        """Enabled fns per tier, in tier order.

        Memoized per (registry, size): dispatch runs per job/task in the
        hot loops while registration only ever ADDS fns during
        on_session_open, so a registry's materialized tier lists are valid
        until its length changes."""
        key = (flag_name, id(fns), len(fns))
        cached = self._tier_fns_cache.get(key)
        if cached is not None:
            return cached
        tiers = []
        for tier in self.tiers:
            out = []
            for plugin in tier.plugins:
                if flag_name is not None and not conf.enabled(getattr(plugin, flag_name)):
                    continue
                fn = fns.get(plugin.name)
                if fn is not None:
                    out.append(fn)
            tiers.append(out)
        self._tier_fns_cache[key] = tiers
        return tiers

    def _victims(self, flag_name: str, fns, claimer, claimees) -> List[TaskInfo]:
        """Within-tier intersection; first deciding tier wins
        (session_plugins.go:106-187)."""
        for tier_fns in self._tier_plugins(flag_name, fns):
            victims: Optional[List[TaskInfo]] = None
            for fn in tier_fns:
                candidates = fn(claimer, claimees)
                if victims is None:
                    victims = candidates
                else:
                    cand_uids = {c.uid for c in (candidates or [])}
                    victims = [v for v in victims if v.uid in cand_uids]
            if victims is not None:
                return victims
        return []

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]) -> List[TaskInfo]:
        return self._victims("enabled_reclaimable", self.reclaimable_fns, reclaimer, reclaimees)

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]) -> List[TaskInfo]:
        return self._victims("enabled_preemptable", self.preemptable_fns, preemptor, preemptees)

    def overused(self, queue: QueueInfo) -> bool:
        """OR over all plugins, no enable flag (session_plugins.go:191-205)."""
        for tier_fns in self._tier_plugins(None, self.overused_fns):
            for fn in tier_fns:
                if fn(queue):
                    return True
        return False

    def job_ready(self, job: JobInfo) -> bool:
        for tier_fns in self._tier_plugins("enabled_job_ready", self.job_ready_fns):
            for fn in tier_fns:
                if not fn(job):
                    return False
        return True

    def job_pipelined(self, job: JobInfo) -> bool:
        for tier_fns in self._tier_plugins("enabled_job_pipelined", self.job_pipelined_fns):
            for fn in tier_fns:
                if not fn(job):
                    return False
        return True

    def job_valid(self, job: JobInfo):
        # preempt/reclaim/backfill each dispatch this once per job; when
        # every registered validator declares itself a pure function of the
        # job's status index (the stock gang one does), the verdict is
        # memoized per (job, _status_version). The gate is keyed to the
        # validator COUNT: open_session_state dispatches job_valid before
        # plugins register, and a memo latched against the empty (or any
        # smaller) fn set must be discarded when registration grows it.
        fns = self.job_valid_fns
        if not fns:
            return None
        gate = self._job_valid_memo
        if gate is None or gate[0] != len(fns):
            memo = ({} if all(getattr(fn, "_status_version_keyed", False)
                              for fn in fns.values()) else False)
            gate = self._job_valid_memo = (len(fns), memo)
        memo = gate[1]
        if memo is not False:
            hit = memo.get(job.uid)
            if hit is not None and hit[0] == job._status_version:
                return hit[1]
        vr_out = None
        for tier_fns in self._tier_plugins(None, fns):
            for fn in tier_fns:
                vr = fn(job)
                if vr is not None and not vr.pass_:
                    vr_out = vr
                    break
            if vr_out is not None:
                break
        if memo is not False:
            memo[job.uid] = (job._status_version, vr_out)
        return vr_out

    def job_enqueueable(self, job: JobInfo) -> bool:
        for tier_fns in self._tier_plugins(None, self.job_enqueueable_fns):
            for fn in tier_fns:
                if not fn(job):
                    return False
        return True

    def _order(self, flag_name: str, fns, l, r) -> int:
        # flattened twin of the _tier_plugins memo: comparators run per
        # PAIR in the priority-queue hot loops, so even the nested-list
        # iteration overhead is worth hoisting (tier order preserved)
        key = (flag_name, id(fns), len(fns))
        flat = self._flat_fns_cache.get(key)
        if flat is None:
            flat = self._flat_fns_cache[key] = [
                fn for tier_fns in self._tier_plugins(flag_name, fns)
                for fn in tier_fns]
        for fn in flat:
            j = fn(l, r)
            if j != 0:
                return j
        return 0

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        j = self._order("enabled_job_order", self.job_order_fns, l, r)
        if j != 0:
            return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def job_order_cmp(self, l: JobInfo, r: JobInfo) -> int:
        """3-way twin of job_order_fn (cmp < 0 iff job_order_fn(l, r)):
        comparator heaps dispatch ONCE per comparison instead of probing
        both directions for equality."""
        j = self._order("enabled_job_order", self.job_order_fns, l, r)
        if j != 0:
            return j
        if l.creation_timestamp == r.creation_timestamp:
            return -1 if l.uid < r.uid else (1 if l.uid > r.uid else 0)
        return -1 if l.creation_timestamp < r.creation_timestamp else 1

    def namespace_order_fn(self, l: str, r: str) -> bool:
        j = self._order("enabled_namespace_order", self.namespace_order_fns, l, r)
        if j != 0:
            return j < 0
        return l < r

    def namespace_order_cmp(self, l: str, r: str) -> int:
        j = self._order("enabled_namespace_order", self.namespace_order_fns, l, r)
        if j != 0:
            return j
        return -1 if l < r else (1 if l > r else 0)

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        j = self._order("enabled_queue_order", self.queue_order_fns, l, r)
        if j != 0:
            return j < 0
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def queue_order_cmp(self, l: QueueInfo, r: QueueInfo) -> int:
        j = self._order("enabled_queue_order", self.queue_order_fns, l, r)
        if j != 0:
            return j
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return -1 if l.uid < r.uid else (1 if l.uid > r.uid else 0)
        return -1 if lt < rt else 1

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        return self._order("enabled_task_order", self.task_order_fns, l, r)

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        lt = l.pod.metadata.creation_timestamp if l.pod else 0
        rt = r.pod.metadata.creation_timestamp if r.pod else 0
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def stock_task_order_key(self):
        """A sort KEY totally ordering tasks exactly like task_order_fn, or
        None when some enabled comparator has no registered key twin
        (add_task_order_fn's ``key``). With a key, hot loops replace
        comparator heaps (one Python dispatch per PAIR) with one C-level
        sort (one key per ITEM). The composed tuple is (plugin keys in tier
        order..., ctime, uid) — the comparator chain plus task_order_fn's
        tie-break. Memoized on the registry size (fns only ADD during
        open)."""
        memo = self._stock_task_key_memo
        if memo is not None and memo[0] == len(self.task_order_fns):
            return memo[1]
        enabled = [
            plugin.name
            for tier in self.tiers
            for plugin in tier.plugins
            if conf.enabled(plugin.enabled_task_order)
            and plugin.name in self.task_order_fns
        ]
        if any(name not in self.task_order_keys for name in enabled):
            key = None
        else:
            plugin_keys = [self.task_order_keys[name] for name in enabled]
            if not plugin_keys:
                key = lambda t: (  # noqa: E731
                    t.pod.metadata.creation_timestamp if t.pod else 0, t.uid)
            elif len(plugin_keys) == 1:
                k0 = plugin_keys[0]
                key = lambda t: (  # noqa: E731
                    k0(t),
                    t.pod.metadata.creation_timestamp if t.pod else 0,
                    t.uid)
            else:
                key = lambda t: (  # noqa: E731
                    *(k(t) for k in plugin_keys),
                    t.pod.metadata.creation_timestamp if t.pod else 0,
                    t.uid)
        self._stock_task_key_memo = (len(self.task_order_fns), key)
        return key

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """Chains all enabled predicates; raises FitFailure on first miss."""
        for tier_fns in self._tier_plugins("enabled_predicate", self.predicate_fns):
            for fn in tier_fns:
                fn(task, node)

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        score = 0.0
        for tier_fns in self._tier_plugins("enabled_node_order", self.node_order_fns):
            for fn in tier_fns:
                score += fn(task, node)
        return score

    def batch_node_order_fn(self, task: TaskInfo, nodes: List[NodeInfo]) -> Dict[str, float]:
        scores: Dict[str, float] = {}
        for tier_fns in self._tier_plugins("enabled_node_order", self.batch_node_order_fns):
            for fn in tier_fns:
                for node_name, s in fn(task, nodes).items():
                    scores[node_name] = scores.get(node_name, 0.0) + s
        return scores

    def node_order_map_fn(self, task: TaskInfo, node: NodeInfo):
        """Returns ({plugin: score}, summed order score) (session_plugins.go:474).

        The (plugin, order fn, map fn) triples are resolved once per
        registry size — this dispatch runs per (task, node) in the serial
        prioritize sweep, and re-walking the tier/flag structure per node
        dominates the actual scoring lambdas."""
        key = (len(self.node_order_fns), len(self.node_map_fns))
        cached = self._node_order_pairs_cache
        if cached is None or cached[0] != key:
            pairs = []
            for tier in self.tiers:
                for plugin in tier.plugins:
                    if not conf.enabled(plugin.enabled_node_order):
                        continue
                    fn = self.node_order_fns.get(plugin.name)
                    mfn = self.node_map_fns.get(plugin.name)
                    if fn is not None or mfn is not None:
                        pairs.append((plugin.name, fn, mfn))
            cached = self._node_order_pairs_cache = (key, pairs)
        node_score_map: Dict[str, float] = {}
        priority_score = 0.0
        for name, fn, mfn in cached[1]:
            if fn is not None:
                priority_score += fn(task, node)
            if mfn is not None:
                node_score_map[name] = mfn(task, node)
        return node_score_map, priority_score

    def node_order_reduce_fn(self, task: TaskInfo, plugin_node_scores: Dict[str, Dict[str, float]]) -> Dict[str, float]:
        node_scores: Dict[str, float] = {}
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not conf.enabled(plugin.enabled_node_order):
                    continue
                rfn = self.node_reduce_fns.get(plugin.name)
                if rfn is None:
                    continue
                scores = plugin_node_scores.get(plugin.name, {})
                rfn(task, scores)
                for host, s in scores.items():
                    node_scores[host] = node_scores.get(host, 0.0) + s
        return node_scores

    # ------------------------------------------------------------------
    # mutation API (session.go:198-369)
    # ------------------------------------------------------------------

    def statement(self):
        from volcano_tpu.scheduler.framework.statement import Statement

        return Statement(self)

    def fast_trans(self):
        """The session's native transition engine (ops/fasttrans.py), or
        None when the handler set is not the recognized stock set. Built
        once, after plugins have registered (actions run later)."""
        if self._fast_trans is False:
            from volcano_tpu.ops import fasttrans

            self._fast_trans = fasttrans.build(self)
        return self._fast_trans

    def _fire_allocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Place onto releasing resources; session-state only (session.go:205-245)."""
        self._placement_gen += 1
        ft = self.fast_trans()
        if ft is not None:
            ft.pipeline(task, hostname, strict=True)
            return
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._fire_allocate(task)

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Allocate onto idle resources; dispatches the whole job when it
        becomes gang-ready (session.go:248-303)."""
        self.cache.allocate_volumes(task, hostname)
        self._placement_gen += 1
        ft = self.fast_trans()
        if ft is not None:
            job = ft.allocate(task, hostname)
        else:
            job = self.jobs.get(task.job)
            if job is None:
                raise KeyError(f"failed to find job {task.job}")
            job.update_task_status(task, TaskStatus.ALLOCATED)
            task.node_name = hostname
            node = self.nodes.get(hostname)
            if node is None:
                raise KeyError(f"failed to find node {hostname}")
            node.add_task(task)
            self._fire_allocate(task)

        if self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()):
                self.dispatch(t)

    def dispatch(self, task: TaskInfo) -> None:
        """(session.go:305-329)"""
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.BINDING)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """(session.go:332-369)"""
        self.cache.evict(reclaimee, reason)
        ft = self.fast_trans()
        if ft is not None:
            ft.evict(reclaimee, strict=True)
            return
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self._fire_deallocate(reclaimee)

    def update_job_condition(self, job_info: JobInfo, cond: objects.PodGroupCondition) -> None:
        """(session.go:372-394)"""
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(f"failed to find job {job_info.namespace}/{job_info.name}")
        for i, c in enumerate(job.pod_group.status.conditions):
            if c.type == cond.type:
                job.pod_group.status.conditions[i] = cond
                return
        job.pod_group.status.conditions.append(cond)


def job_status_values(ssn: Session, job_info: JobInfo):
    """The (phase, running, failed, succeeded) a session-close writeback
    would set (session.go:157-195) — the value half of job_status, without
    materializing the status clone (JobUpdater skips the clone when these
    equal the live status)."""
    idx = job_info.task_status_index
    cur = job_info.pod_group.status
    unschedulable = any(
        c.type == objects.POD_GROUP_UNSCHEDULABLE_TYPE
        and c.status == "True"
        and c.transition_id == ssn.uid
        for c in cur.conditions
    )

    phase = cur.phase
    if idx.get(TaskStatus.RUNNING) and unschedulable:
        phase = objects.PodGroupPhase.UNKNOWN
    else:
        allocated = 0
        for st, tasks in idx.items():
            if allocated_status(st) or st == TaskStatus.SUCCEEDED:
                allocated += len(tasks)
        if allocated >= job_info.pod_group.spec.min_member:
            phase = objects.PodGroupPhase.RUNNING
        elif cur.phase != objects.PodGroupPhase.INQUEUE:
            phase = objects.PodGroupPhase.PENDING

    return (phase,
            len(idx.get(TaskStatus.RUNNING, {})),
            len(idx.get(TaskStatus.FAILED, {})),
            len(idx.get(TaskStatus.SUCCEEDED, {})))


def job_status(ssn: Session, job_info: JobInfo) -> objects.PodGroupStatus:
    """Compute the PodGroup status to write back at session close
    (session.go:157-195)."""
    status = job_info.pod_group.status.clone()
    (status.phase, status.running, status.failed,
     status.succeeded) = job_status_values(ssn, job_info)
    return status


def open_session_state(ssn: Session) -> None:
    """Fill the session from the cache snapshot and drop invalid jobs
    (session.go:72-139)."""
    snapshot: ClusterInfo = ssn.cache.snapshot()
    ssn.jobs = snapshot.jobs
    for job in list(ssn.jobs.values()):
        if job.pod_group is not None and job.pod_group.status.conditions:
            ssn.pod_group_status[job.uid] = job.pod_group.status.clone()
        vjr = ssn.job_valid(job)
        if vjr is not None:
            if not vjr.pass_:
                jc = objects.PodGroupCondition(
                    type=objects.POD_GROUP_UNSCHEDULABLE_TYPE,
                    status="True",
                    transition_id=ssn.uid,
                    reason=vjr.reason,
                    message=vjr.message,
                )
                try:
                    ssn.update_job_condition(job, jc)
                except (KeyError, AttributeError):
                    pass
            del ssn.jobs[job.uid]
    ssn.nodes = snapshot.nodes
    ssn.queues = snapshot.queues
    ssn.namespace_info = snapshot.namespace_info
    ssn.node_axis = snapshot.node_axis
