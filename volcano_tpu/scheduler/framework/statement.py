"""Statement — the per-job operation log with commit/rollback; THE gang
atomicity mechanism (volcano pkg/scheduler/framework/statement.go).

Operations (allocate/pipeline/evict) mutate *session* state eagerly and are
logged; ``commit`` flushes them to the cache (bind/evict effectors), while
``discard`` undoes them in reverse order, restoring session state so a
partially-placed gang leaves no trace.
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.framework.event_handlers import Event

logger = logging.getLogger(__name__)


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []
        # native transition engine (None => every op runs the Python body
        # below, which remains the behavioral oracle)
        self._ft = ssn.fast_trans()

    # -- evict -------------------------------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Session-state eviction, logged (statement.go:40-72)."""
        if self._ft is not None:
            self._ft.evict(reclaimee, strict=False)
            self.operations.append(("evict", (reclaimee, reason)))
            return
        ssn = self.ssn
        job = ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        ssn._fire_deallocate(reclaimee)
        self.operations.append(("evict", (reclaimee, reason)))

    def _commit_evict(self, reclaimee: TaskInfo, reason: str) -> None:
        try:
            self.ssn.cache.evict(reclaimee, reason)
        except Exception as e:
            logger.error("failed to evict task %s/%s: %s", reclaimee.namespace, reclaimee.name, e)
            self._unevict(reclaimee)

    def _unevict(self, reclaimee: TaskInfo) -> None:
        if self._ft is not None:
            self._ft.unevict(reclaimee)
            return
        ssn = self.ssn
        job = ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
        node = ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            # The reference calls AddTask here and silently drops its
            # "already on node" error (statement.go:100-102), leaving the
            # node's Releasing accounting inflated for the rest of the
            # session. We restore it properly instead.
            node.update_task(reclaimee)
        ssn._fire_allocate(reclaimee)

    # -- pipeline ----------------------------------------------------------

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """(statement.go:116-156)"""
        self.ssn._placement_gen += 1
        if self._ft is not None:
            self._ft.pipeline(task, hostname, strict=False)
            self.operations.append(("pipeline", (task, hostname)))
            return
        ssn = self.ssn
        job = ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = ssn.nodes.get(hostname)
        if node is not None:
            try:
                node.add_task(task)
            except RuntimeError as e:
                logger.error("failed to pipeline task %s to %s: %s", task.name, hostname, e)
        ssn._fire_allocate(task)
        self.operations.append(("pipeline", (task, hostname)))

    def _unpipeline(self, task: TaskInfo) -> None:
        self.ssn._placement_gen += 1
        if self._ft is not None:
            self._ft.unpipeline(task)
            return
        ssn = self.ssn
        job = ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = ssn.nodes.get(task.node_name)
        if node is not None:
            try:
                node.remove_task(task)
            except RuntimeError as e:
                logger.error("failed to unpipeline task %s: %s", task.name, e)
        task.node_name = ""
        ssn._fire_deallocate(task)

    # -- allocate ----------------------------------------------------------

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Session-state allocation, logged (statement.go:199-251)."""
        ssn = self.ssn
        ssn._placement_gen += 1
        ssn.cache.allocate_volumes(task, hostname)
        job = ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        ssn._fire_allocate(task)
        self.operations.append(("allocate", (task, hostname)))

    def _commit_allocate(self, task: TaskInfo, hostname: str) -> None:
        # Per-operation failures must not abort the rest of the commit
        # (statement.go:325-340 ignores them) — other gang members still bind.
        try:
            self.ssn.cache.bind_volumes(task)
            self.ssn.cache.bind(task, task.node_name)
        except Exception as e:
            logger.error("failed to bind task %s/%s: %s", task.namespace, task.name, e)
            return
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.BINDING)

    def _unallocate(self, task: TaskInfo, reason: str) -> None:
        ssn = self.ssn
        ssn._placement_gen += 1
        # release any volume assumption made by allocate's
        # cache.allocate_volumes (bound volumes are untouched)
        unassume = getattr(ssn.cache.volume_binder, "unassume", None)
        if unassume is not None:
            unassume(task)
        job = ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = ssn.nodes.get(task.node_name)
        if node is not None:
            try:
                node.remove_task(task)
            except RuntimeError as e:
                logger.error("failed to unallocate task %s: %s", task.name, e)
        task.node_name = ""
        ssn._fire_deallocate(task)

    # -- commit/rollback (statement.go:309-337) ----------------------------

    def discard(self) -> None:
        """Reverse-order undo of every logged operation."""
        for name, args in reversed(self.operations):
            if name == "evict":
                self._unevict(args[0])
            elif name == "pipeline":
                self._unpipeline(args[0])
            elif name == "allocate":
                self._unallocate(args[0], "discarded")
        self.operations = []

    def commit(self) -> None:
        """Flush logged operations to the cache effectors."""
        for name, args in self.operations:
            if name == "evict":
                self._commit_evict(*args)
            elif name == "pipeline":
                pass  # pipelined placement stays session-local (statement.go:158)
            elif name == "allocate":
                self._commit_allocate(*args)
        self.operations = []
