"""Session event handlers (volcano pkg/scheduler/framework/event_handlers.go).

Plugins register allocate/deallocate callbacks to keep incremental state
(DRF shares, proportion allocations) in sync with session mutations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional


@dataclass
class Event:
    task: object  # TaskInfo


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
    # stock plugins tag their handlers so the native transition engine
    # (ops/fasttrans.py) can recognize — and fuse — exactly the handler
    # set it models; any untagged handler disables the fast path
    origin: Optional[tuple] = None
