"""Scheduler framework: session lifecycle, tiered plugin dispatch,
transactional statements, and the plugin/action registries."""

from volcano_tpu.scheduler.framework.interface import Action, Plugin
from volcano_tpu.scheduler.framework.plugins import (
    get_action,
    get_plugin_builder,
    register_action,
    register_plugin_builder,
)
from volcano_tpu.scheduler.framework.arguments import Arguments
from volcano_tpu.scheduler.framework.event_handlers import Event, EventHandler
from volcano_tpu.scheduler.framework.session import Session
from volcano_tpu.scheduler.framework.statement import Statement
from volcano_tpu.scheduler.framework.framework import (
    open_session,
    close_session,
    run_actions,
    takeover_recovery_sweep,
)
