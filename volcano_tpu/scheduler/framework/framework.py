"""Session lifecycle (volcano pkg/scheduler/framework/framework.go:30-62)."""

from __future__ import annotations

import logging
import time
from typing import List

from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.scheduler import conf
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.framework.arguments import Arguments
from volcano_tpu.scheduler.framework.job_updater import JobUpdater
from volcano_tpu.scheduler.framework.plugins import get_plugin_builder
from volcano_tpu.scheduler.framework.session import Session, open_session_state

logger = logging.getLogger(__name__)


def open_session(cache, tiers: List[conf.Tier]) -> Session:
    ssn = Session(cache)
    # snapshot happens before tiers are installed (so the open-time JobValid
    # pass is a no-op — actions re-validate; matches framework.go:31-32)
    open_session_state(ssn)
    # conf loading normally defaults the enable flags (util.go:59); defaulting
    # again here is idempotent and protects hand-built tiers.
    for tier in tiers:
        for option in tier.plugins:
            conf.apply_plugin_conf_defaults(option)
    ssn.tiers = tiers

    for tier in tiers:
        for plugin_option in tier.plugins:
            builder = get_plugin_builder(plugin_option.name)
            if builder is None:
                logger.error("Failed to get plugin %s.", plugin_option.name)
                continue
            plugin = builder(Arguments(plugin_option.arguments))
            ssn.plugins[plugin.name()] = plugin

    for plugin in ssn.plugins.values():
        start = time.perf_counter()
        plugin.on_session_open(ssn)
        metrics.update_plugin_duration(plugin.name(), "OnSessionOpen", time.perf_counter() - start)
    return ssn


def takeover_recovery_sweep(ssn) -> int:
    """First session of a new leadership term: revert the half-bound gangs
    a deposed leader's fenced mid-chain abort may have left in the store.

    A leader killed between two binds of one gang's fused chain (or serial
    Statement commit) leaves 0 < bound < minAvailable pods with node_name
    set — pods the deposed term can no longer touch (its writes are
    fenced) and that would otherwise violate gang atomicity until chance
    capacity completes them. The new term evicts them through the ordinary
    Statement machinery (same fidelity as an express revert: events, cache
    accounting, dirty-sets, metrics), freeing the capacity for THIS
    session's own placements; the job controller's normal recovery
    resubmits the gang for atomic re-placement. Jobs with any terminal
    task are lifecycle churn, not failover residue — skipped, exactly as
    the auditor's gang rule exempts them. Returns gangs reverted."""
    terminal = TaskStatus.SUCCEEDED | TaskStatus.FAILED
    reverted = 0
    for job_uid in sorted(ssn.jobs):
        job = ssn.jobs[job_uid]
        if job.min_available <= 1:
            continue
        tasks = [job.tasks[uid] for uid in sorted(job.tasks)]
        if any(t.status & terminal for t in tasks):
            continue
        bound = [t for t in tasks
                 if allocated_status(t.status) and t.node_name]
        if not bound or len(bound) >= job.min_available:
            continue
        stmt = ssn.statement()
        for task in bound:
            stmt.evict(task, "takeover-recovery: gang short after failover")
        stmt.commit()
        reverted += 1
    if reverted:
        logger.warning(
            "takeover recovery: reverted %d half-bound gang(s) left by a "
            "deposed leader", reverted)
    return reverted


def run_actions(ssn: Session, actions) -> dict:
    """Run the session's action chain, preferring the whole-session fused
    dispatch (ops/session_fuse.py) when the session is inside its envelope;
    otherwise the plain per-action loop. ``actions`` is a sequence of
    action names or Action instances. Returns {action name: wall ms} — the
    per-action timings every caller (scheduler loop, bench, simulator) used
    to collect itself."""
    from volcano_tpu.scheduler.framework.plugins import get_action

    names = [a if isinstance(a, str) else a.name() for a in actions]
    if getattr(ssn.cache, "express_lane", None) is not None:
        # reconcile every outstanding express bind FIRST: the session is
        # the fairness/preemption authority, and reverts must free their
        # capacity before this session's own placement decisions encode
        from volcano_tpu.express.reconcile import reconcile_session

        ssn.cache.express_lane.set_tiers(ssn.tiers)
        reconcile_session(ssn)
    if getattr(ssn.cache, "fence_sweep_due", False):
        # one recovery sweep per leadership term, before any placement
        ssn.cache.fence_sweep_due = False
        takeover_recovery_sweep(ssn)
    try:
        from volcano_tpu.ops import session_fuse
    except Exception:  # pragma: no cover - jax-free host
        session_fuse = None
    if session_fuse is not None:
        out = session_fuse.try_run(ssn, names)
        if out is not None:
            return out
    action_ms = {}
    for name in names:
        t0 = time.perf_counter()
        get_action(name).execute(ssn)
        action_ms[name] = round((time.perf_counter() - t0) * 1e3, 3)
    return action_ms


def close_session(ssn: Session) -> None:
    # apply any cache-mirror work the bulk writeback deferred off the
    # in-session critical path (solver._apply_bulk; the reference's bind
    # is async and its cache syncs from later watch events) — plugins'
    # on_session_close and the job updater read the cache below
    flush = getattr(ssn.cache, "flush_mirror", None)
    if flush is not None:
        flush()
    # volume assumptions not bound by session end belong to placements
    # that never dispatched (e.g. a gang that stayed short) — release
    # them, or their PVs stay unselectable forever (assume/bind always
    # completes within one session; see StoreVolumeBinder)
    vb = getattr(ssn.cache, "volume_binder", None)
    reset_assumed = getattr(vb, "reset_assumptions", None)
    if reset_assumed is not None:
        reset_assumed()
    for plugin in ssn.plugins.values():
        start = time.perf_counter()
        plugin.on_session_close(ssn)
        metrics.update_plugin_duration(plugin.name(), "OnSessionClose", time.perf_counter() - start)

    JobUpdater(ssn).update_all()

    ssn.jobs = {}
    ssn.nodes = {}
    ssn.node_axis = None  # releases the snapshot's cloned NodeInfos too
    ssn.plugins = {}
    ssn.event_handlers = []
    ssn.job_order_fns = {}
    ssn.namespace_order_fns = {}
    ssn.queue_order_fns = {}
