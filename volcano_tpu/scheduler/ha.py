"""Active/standby failover: warm standby follow + fenced takeover.

The elector (scheduler/leaderelection.py) decides WHO leads; this module
makes losing and gaining leadership SAFE and FAST:

- **fenced takeover** — on every acquisition the new epoch
  (elector.epoch(), the lease record's transition count + 1) is stamped
  onto the scheduler cache's effector write-path BEFORE the session loop
  starts, and the store (store/store.py) rejects any write still carrying
  an older epoch. A deposed leader mid-fused-chain or mid-express-commit
  therefore aborts through the ordinary effector-failure machinery
  (statement rewind, resync, express token drain) instead of
  double-binding — Omega-style optimistic concurrency stays safe across
  leader transitions;

- **warm standby** — while NOT leading, the scheduler's cache keeps
  following the watch stream (it mirrors synchronously by construction)
  and a follow loop keeps the expensive session-open state warm: the
  SnapshotKeeper's incremental snapshot, the long-lived node axis, and —
  because snapshots feed the same encoder buffers — the identity-token
  caches the warm path relies on. Takeover then opens its first session
  incrementally (zero wholesale snapshot rebuilds) and, in-process or
  with pre-warmed kernels, with zero recompiles. The express lane stays
  PARKED while standby (tokens and queue survive for the first led
  session to reconcile/drain).

The simulator drives the same promote sequence deterministically
(sim/harness.py HA mode) and audits the takeover bound + fencing balance
continuously (sim/auditor.py ha_fencing / ha_takeover rules).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from volcano_tpu.scheduler.leaderelection import LeaderElector, ResourceLock

logger = logging.getLogger(__name__)


class WarmStandby:
    """Keeps a non-leading scheduler session-ready.

    ``follow_once()`` builds (and discards) a snapshot: the keeper
    re-clones only what moved since the last follow, the node axis is
    patched row-wise, and deletion churn is absorbed continuously — so
    the first POST-takeover session pays an incremental open, not the
    wholesale rebuild a cold cache would. ``start()`` runs it on a
    daemon thread between ``resume()``/``pause()`` (paused while this
    instance leads — live sessions snapshot for themselves)."""

    def __init__(self, cache, follow_period: float = 1.0):
        self.cache = cache
        self.follow_period = float(follow_period)
        self.stats: Dict[str, int] = {"follows": 0, "errors": 0}
        self._stop = threading.Event()
        self._following = threading.Event()
        self._following.set()
        self._thread: Optional[threading.Thread] = None

    def follow_once(self) -> None:
        self.cache.snapshot()
        self.stats["follows"] += 1

    def start(self) -> "WarmStandby":
        self.cache.run()
        self.cache.wait_for_cache_sync()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ha-warm-standby")
        self._thread.start()
        return self

    def pause(self) -> None:
        """Leading now: sessions keep the keeper warm themselves."""
        self._following.clear()

    def resume(self) -> None:
        self._following.set()

    def stop(self) -> None:
        self._stop.set()
        self._following.set()  # release a paused waiter
        if self._thread is not None:
            self._thread.join(timeout=self.follow_period + 1.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._following.wait()
            if self._stop.is_set():
                return
            try:
                self.follow_once()
            except Exception:
                # a follow failure costs warmth, never correctness — the
                # next follow (or the takeover session) rebuilds honestly
                self.stats["errors"] += 1
                logger.exception("warm-standby follow failed")
            self._stop.wait(self.follow_period)


class FailoverScheduler:
    """One HA member: a Scheduler + elector + warm standby, wired so that

    - acquisition stamps the fence epoch, pauses the follow loop, unparks
      the express lane, and starts the session loop;
    - loss stops the loop (cache stays attached and hot), parks the
      express lane, and resumes following;
    - the deposed term's writes keep their stale stamp (the elector never
      regresses its epoch), so anything still in flight is fenced.

    This is the production-shaped twin of the simulator's deterministic
    promote path; tests drive both against one store."""

    def __init__(self, scheduler, store,
                 lock_namespace: str = "volcano-system",
                 lock_name: str = "vc-scheduler",
                 identity: str = "",
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 5.0,
                 follow_period: float = 1.0):
        import os
        import socket

        self.scheduler = scheduler
        self.store = store
        identity = identity or f"{socket.gethostname()}-{os.getpid()}"
        if getattr(scheduler, "_pipeline", False):
            # a standby for a PIPELINED scheduler must keep BOTH halves
            # of the snapshot buffer pair warm: with the pair armed, each
            # follow_once alternates buffers, so the first led cycle (and
            # its first solve-ahead) both open incrementally — enabling
            # the pair only at takeover would pay a wholesale rebuild for
            # the second buffer right inside the takeover bound
            try:
                from volcano_tpu.pipeline import pipeline_enabled

                if pipeline_enabled():
                    scheduler.cache.enable_pipeline()
            except Exception:  # pragma: no cover - jax-free host
                pass
        self.standby = WarmStandby(scheduler.cache, follow_period)
        self.elector = LeaderElector(
            ResourceLock(store, lock_namespace, lock_name, identity),
            on_started_leading=self._on_acquired,
            on_stopped_leading=self._on_lost,
            lease_duration=lease_duration,
            renew_deadline=renew_deadline,
            retry_period=retry_period)
        self.transitions: List[Dict[str, float]] = []

    # -- elector callbacks (elector thread) ---------------------------------

    def _on_acquired(self) -> None:
        epoch = self.elector.epoch()
        self.standby.pause()
        self.scheduler.set_fence_epoch(epoch)
        self.scheduler.run()
        self.transitions.append({"epoch": epoch, "at": time.time()})
        logger.info("takeover complete: leading at epoch %d", epoch)

    def _on_lost(self) -> None:
        # cache stays attached + hot (stop_cache=False): this member is
        # the warm standby for the next transition; the stale fence stamp
        # stays on the effectors until the next acquisition replaces it
        self.scheduler.stop(stop_cache=False)
        self.standby.resume()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FailoverScheduler":
        self.standby.start()
        self.elector.start()
        return self

    def stop(self) -> None:
        self.elector.stop()
        self.standby.stop()

    def is_leader(self) -> bool:
        return self.elector.is_leader()

    def healthy(self) -> bool:
        return self.elector.healthy()
