"""SnapshotKeeper — delta-maintained session snapshot.

The reference rebuilds its snapshot wholesale every session
(cache.go:713-798) and round-5 measured that faithfulness at ~152 ms of
host Python per cycle at 50k tasks x 10k nodes — more than the entire
device solve. But this cache already receives TYPED deltas (watch events,
effector calls, the deferred bulk-mirror flush), so the keeper maintains
the snapshot between sessions and rebuilds only what actually moved:

- **dirty-sets** — every cache mutation path (watch handlers, bind/evict
  effectors, resyncs) marks the touched job uid / node name; at the next
  ``snapshot()`` only those entries are re-cloned from the cache;
- **session-mutation detection** — the keeper records each handed-out
  clone's ``_status_version`` / ``_acct_gen``; a session that mutated an
  object through the Statement path (allocate/evict/pipeline and their
  unwinds) leaves the version ahead of the record and the object is
  re-cloned.  Pipelined placements in particular are session-only state
  and MUST revert to the cache's truth each cycle — the version gap is
  what reverts them;
- **bulk-flush sync** — the rounds writeback's deferred mirror flush
  (cache.flush_mirror) applies the session's own placements to the cache
  trees, after which snapshot object == cache object for everything it
  flipped.  The flush re-records those versions (``sync_job``/``sync_node``
  with the versions captured at defer time, solver._apply_bulk), so a
  steady-state bulk cycle reuses its whole snapshot instead of re-cloning
  50k tasks.  Any task the flush could NOT flip (deleted in the defer
  window) re-dirties its job and node;
- **generation counter** — structural changes the dirty-sets don't model
  (queue set, priority classes) bump ``generation``; the next snapshot
  falls back to a full rebuild, exactly the wholesale path.  A remote
  watch reset floods the handlers with re-ADDs, which mark everything
  dirty — equivalent to a rebuild without a special case.

Reuse safety: a reused JobInfo/NodeInfo is handed to the next session
as-is, so per-session scratch (fit errors) is cleared on reuse, and the
bulk writeback's task-sharing into node maps stays safe because the only
in-place task mutations sessions perform target PENDING (bulk/Statement
allocate) or RUNNING (preempt/reclaim victims) tasks — never the shared
BINDING set, whose status only moves via watch events, which dirty the
owning job and node and force a re-clone.

The columnar node axis (nodeaxis.py) is promoted to a long-lived
structure the same way: rows are refreshed in place for re-cloned /
session-mutated nodes and the whole axis is recaptured only when the
ready-node membership changes.

``VOLCANO_TPU_WHOLESALE_SNAPSHOT=1`` disables the keeper (every snapshot
is a full rebuild — the round-5 behavior and the parity oracle).
"""

from __future__ import annotations

import os
from typing import Dict, Set

import numpy as np

from volcano_tpu.api.cluster_info import ClusterInfo
from volcano_tpu.scheduler.cache.nodeaxis import (
    capture_node_axis,
    refresh_rows,
)


class DirtyShadow:
    """A second consumer of the keeper's dirty marks (the express lane's
    live-axis maintenance, express/encode.py): every mark_job/mark_node
    lands in each registered shadow too, so a between-sessions consumer
    can drain its own copy without racing ``snapshot()`` for the keeper's
    sets. ``generation`` mirrors the keeper's wholesale-rebuild signal."""

    __slots__ = ("dirty_jobs", "dirty_nodes", "generation")

    def __init__(self):
        self.dirty_jobs: Set[str] = set()
        self.dirty_nodes: Set[str] = set()
        self.generation = 0


class _SnapshotBuffer:
    """One snapshot buffer's private state (the pipeline's double-buffer
    half). The keeper's live buffer lives directly on the keeper (the
    pre-pipeline layout, untouched for single-buffer users); ``swap()``
    exchanges the keeper's live fields with a parked ``_SnapshotBuffer``
    so two consecutive sessions never share clone objects."""

    __slots__ = ("jobs", "nodes", "job_vers", "node_gens",
                 "dirty_jobs", "dirty_nodes", "axis", "built_generation")

    def __init__(self):
        self.jobs: Dict[str, object] = {}
        self.nodes: Dict[str, object] = {}
        self.job_vers: Dict[str, int] = {}
        self.node_gens: Dict[str, int] = {}
        self.dirty_jobs: Set[str] = set()
        self.dirty_nodes: Set[str] = set()
        self.axis = None
        self.built_generation = -1


class SnapshotKeeper:
    def __init__(self):
        self.enabled = not os.environ.get("VOLCANO_TPU_WHOLESALE_SNAPSHOT")
        self.jobs: Dict[str, object] = {}    # uid -> clone in the live snap
        self.nodes: Dict[str, object] = {}   # name -> clone (ready only)
        self.job_vers: Dict[str, int] = {}   # uid -> in-sync _status_version
        self.node_gens: Dict[str, int] = {}  # name -> in-sync _acct_gen
        self.dirty_jobs: Set[str] = set()
        self.dirty_nodes: Set[str] = set()
        self.shadows: list = []   # DirtyShadow fan-out (express lane)
        self.generation = 0       # bump => next snapshot fully rebuilds
        self._built_generation = -1
        self.axis = None
        # delta fingerprint for the pipeline's speculative solve-ahead:
        # every mark/invalidate bumps it, so (dirty_epoch, generation)
        # captured at dispatch and re-checked before apply detects ANY
        # state movement the speculative snapshot did not see
        self.dirty_epoch = 0
        # mark journal (read-set-scoped speculation): when armed, every
        # dirty_epoch bump appends exactly one typed entry — ("job", uid),
        # ("node", name), ("meta", kind, uid) or ("gen",) — so a consumer
        # that captured dirty_epoch at seal can later ask WHICH rows moved
        # (marks_since) instead of only THAT something moved. The journal
        # is bounded: a front trim advances journal_base, and any cursor
        # behind the base (or an epoch bump that bypassed the journal)
        # makes the window unprovable — marks_since then returns None and
        # the caller must degrade to the whole-fingerprint discard.
        self.journal_enabled = False
        self.journal: list = []
        self.journal_base = 0
        self.JOURNAL_CAP = 8192
        # pipeline double-buffer: when armed (enable_pair), marks land in
        # BOTH buffers' dirty sets and swap() alternates which buffer the
        # next snapshot builds — session N and session N+1 then never
        # share clone objects, so N's close can still read its snapshot
        # while N+1's is already open
        self._standby: "_SnapshotBuffer | None" = None
        self.stats = {"rebuilds": 0, "incremental": 0,
                      "reused_jobs": 0, "cloned_jobs": 0,
                      "reused_nodes": 0, "cloned_nodes": 0,
                      "axis_rebuilds": 0, "axis_rows_refreshed": 0,
                      "evict_marks": 0, "swaps": 0}

    # -- pipeline buffer pair ------------------------------------------------

    @property
    def pair_enabled(self) -> bool:
        return self._standby is not None

    def enable_pair(self) -> None:
        """Arm the double buffer (idempotent). The standby starts with
        built_generation=-1, so its first build is a wholesale rebuild —
        after that both buffers delta-maintain independently."""
        if self._standby is None:
            self._standby = _SnapshotBuffer()

    def swap(self) -> None:
        """Exchange the live buffer with the standby (caller holds the
        cache lock). No-op until enable_pair()."""
        sb = self._standby
        if sb is None:
            return
        (self.jobs, sb.jobs) = (sb.jobs, self.jobs)
        (self.nodes, sb.nodes) = (sb.nodes, self.nodes)
        (self.job_vers, sb.job_vers) = (sb.job_vers, self.job_vers)
        (self.node_gens, sb.node_gens) = (sb.node_gens, self.node_gens)
        (self.dirty_jobs, sb.dirty_jobs) = (sb.dirty_jobs, self.dirty_jobs)
        (self.dirty_nodes, sb.dirty_nodes) = (
            sb.dirty_nodes, self.dirty_nodes)
        (self.axis, sb.axis) = (sb.axis, self.axis)
        (self._built_generation, sb.built_generation) = (
            sb.built_generation, self._built_generation)
        self.stats["swaps"] += 1

    # -- marks (called under the cache lock) --------------------------------

    def add_shadow(self) -> DirtyShadow:
        """Register an express-lane dirty-set shadow; it receives every
        subsequent mark. Start dirty via generation so the first consumer
        refresh is a wholesale rebuild."""
        sh = DirtyShadow()
        sh.generation = -1
        self.shadows.append(sh)
        return sh

    def drop_shadow(self, sh: DirtyShadow) -> None:
        if sh in self.shadows:
            self.shadows.remove(sh)

    def mark_job(self, uid: str) -> None:
        if uid:
            self.dirty_jobs.add(uid)
            self.dirty_epoch += 1
            if self.journal_enabled:
                self._journal(("job", uid))
            if self._standby is not None:
                self._standby.dirty_jobs.add(uid)
            for sh in self.shadows:
                sh.dirty_jobs.add(uid)

    def mark_node(self, name: str) -> None:
        if name:
            self.dirty_nodes.add(name)
            self.dirty_epoch += 1
            if self.journal_enabled:
                self._journal(("node", name))
            if self._standby is not None:
                self._standby.dirty_nodes.add(name)
            for sh in self.shadows:
                sh.dirty_nodes.add(name)

    def mark_evict(self, job_uid: str, node_name: str) -> None:
        """Eviction effector path: dirty both sides of the eviction in one
        call and count it — the batched eviction replays land here exactly
        like the serial walk, which is what keeps the next incremental
        snapshot honest about RELEASING tasks."""
        self.mark_job(job_uid)
        self.mark_node(node_name)
        self.stats["evict_marks"] += 1

    def mark_meta(self, kind: str = "", uid: str = "") -> None:
        """A policy-level delta the per-object dirty-sets don't model —
        an existing queue's spec update, a namespace quota change.
        QueueInfos and namespace weights are re-derived fresh every
        snapshot, so no clone needs invalidating; but the pipeline's
        speculative solve-ahead read the OLD policy, so the fingerprint
        epoch must move or a sealed stage could commit against a weight
        the serial order would not have used. ``kind``/``uid`` scope the
        journal entry ("queue"/name, "quota"/namespace) so the read-set
        intersect can tell noise on an id the sealed solve never consumed
        from movement of a policy row it did; an unscoped call journals
        as unknown and the intersect must treat it as a hit."""
        self.dirty_epoch += 1
        if self.journal_enabled:
            self._journal(("meta", kind, uid))

    def invalidate(self) -> None:
        self.generation += 1
        self.dirty_epoch += 1
        if self.journal_enabled:
            self._journal(("gen",))
        for sh in self.shadows:
            sh.generation += 1

    # -- mark journal (read-set-scoped speculation) -------------------------

    def enable_journal(self) -> None:
        """Arm the mark journal (idempotent; caller holds the cache lock).
        Arming anchors the base at the CURRENT dirty_epoch — bumps before
        this moment are deliberately unprovable."""
        if not self.journal_enabled:
            self.journal_enabled = True
            self.journal = []
            self.journal_base = self.dirty_epoch

    def _journal(self, entry) -> None:
        j = self.journal
        j.append(entry)
        if len(j) > self.JOURNAL_CAP:
            drop = len(j) - self.JOURNAL_CAP // 2
            del j[:drop]
            self.journal_base += drop

    def marks_since(self, cursor: int):
        """The typed mark entries for every dirty_epoch bump past
        ``cursor`` (a dirty_epoch captured at seal), oldest first — or
        ``None`` when the window is unprovable: journal disarmed when the
        cursor was taken, cursor trimmed past, or an epoch bump that
        bypassed the journal (entry count must equal the epoch delta
        exactly; anything else means an unjournaled movement and the
        caller degrades to the whole-fingerprint discard)."""
        if not self.journal_enabled:
            return None
        if cursor < self.journal_base:
            return None
        if self.journal_base + len(self.journal) != self.dirty_epoch:
            return None
        return self.journal[cursor - self.journal_base:]

    # -- bulk-flush sync ----------------------------------------------------

    def sync_job(self, uid: str, version: int) -> None:
        """Declare the snapshot job in sync with the cache at `version`
        (the flush just mirrored the session's bulk placements). The sync
        is valid only for the LIVE buffer — its clones ARE the session
        objects the flush mirrored; the standby buffer's clone of the same
        job predates the placement and must re-clone from the flushed
        cache twin at its next turn, so it is dirtied instead."""
        if uid in self.job_vers:
            self.job_vers[uid] = version
        if self._standby is not None:
            self._standby.dirty_jobs.add(uid)

    def sync_node(self, name: str, gen: int) -> None:
        if name in self.node_gens:
            self.node_gens[name] = gen
        if self._standby is not None:
            self._standby.dirty_nodes.add(name)

    # -- snapshot -----------------------------------------------------------

    def snapshot(self, cache) -> ClusterInfo:
        """Build the session snapshot (caller holds the cache lock)."""
        if not self.enabled or self._built_generation != self.generation:
            return self._full_build(cache)
        return self._incremental_build(cache)

    def _job_priority(self, cache, job) -> int:
        if job.pod_group is None:
            return job.priority
        pc = cache.priority_classes.get(
            job.pod_group.spec.priority_class_name)
        return pc.value if pc is not None else cache.default_priority

    def _clone_job(self, cache, job):
        job.priority = self._job_priority(cache, job)
        clone = job.clone()
        self.jobs[clone.uid] = clone
        self.job_vers[clone.uid] = clone._status_version
        return clone

    def _clone_node(self, node):
        clone = node.clone()
        self.nodes[clone.name] = clone
        self.node_gens[clone.name] = clone._acct_gen
        return clone

    def _full_build(self, cache) -> ClusterInfo:
        self.stats["rebuilds"] += 1
        self.jobs = {}
        self.nodes = {}
        self.job_vers = {}
        self.node_gens = {}
        self.dirty_jobs = set()
        self.dirty_nodes = set()
        for node in cache.nodes.values():
            if node.ready():
                self._clone_node(node)
        self.axis = capture_node_axis(self.nodes)
        queues = {q.uid: q.clone() for q in cache.queues.values()}
        for job in cache.jobs.values():
            if job.pod_group is None and job.pdb is None:
                continue  # no scheduling spec
            if job.queue not in queues:
                continue  # queue doesn't exist
            self._clone_job(cache, job)
        self._built_generation = self.generation
        return self._emit(cache, queues)

    def _incremental_build(self, cache) -> ClusterInfo:
        self.stats["incremental"] += 1
        queues = {q.uid: q.clone() for q in cache.queues.values()}

        # ---- nodes: re-clone dirty + session-mutated, reuse the rest ----
        dirty_nodes, self.dirty_nodes = self.dirty_nodes, set()
        membership_changed = False
        recloned: Dict[str, object] = {}
        for name in dirty_nodes:
            cn = cache.nodes.get(name)
            if cn is None or not cn.ready():
                if self.nodes.pop(name, None) is not None:
                    membership_changed = True
                self.node_gens.pop(name, None)
                continue
            if name not in self.nodes:
                membership_changed = True
            recloned[name] = self._clone_node(cn)
        # session-mutated (Statement path / bulk apply the flush didn't
        # sync): the handed-out clone's generation moved past the record
        node_gens = self.node_gens
        for name, node in self.nodes.items():
            if name in recloned:
                continue
            if node._acct_gen != node_gens[name]:
                cn = cache.nodes.get(name)
                if cn is None or not cn.ready():
                    # the cache-side twin vanished/unreadied without a
                    # dirty mark — should not happen; rebuild honestly
                    self.invalidate()
                    return self._full_build(cache)
                recloned[name] = self._clone_node(cn)
        self.stats["cloned_nodes"] += len(recloned)
        self.stats["reused_nodes"] += len(self.nodes) - len(recloned)

        # ---- node axis: patch rows in place, recapture on membership ----
        axis = self.axis
        if membership_changed or axis is None \
                or len(axis.names) != len(self.nodes):
            self.axis = capture_node_axis(self.nodes)
            self.stats["axis_rebuilds"] += 1
        else:
            updates = {}
            if recloned:
                index = {n: i for i, n in enumerate(axis.names)}
                for n, nd in recloned.items():
                    updates[index[n]] = nd
            # rows whose accounting generation moved since capture: nodes
            # the previous session's bulk placements touched (content kept
            # in sync by the mirror flush, but the captured columns are
            # pre-placement) — patch them from the live objects
            n = len(axis.nodes)
            if n:
                cur = np.fromiter(
                    (nd._acct_gen for nd in axis.nodes), np.int64, n)
                for i in np.nonzero(cur != axis.gens)[0].tolist():
                    updates.setdefault(i, axis.nodes[i])
            if updates:
                if refresh_rows(axis, sorted(updates.items())):
                    self.stats["axis_rows_refreshed"] += len(updates)
                else:  # new scalar resource dimension: columns reshape
                    self.axis = capture_node_axis(self.nodes)
                    self.stats["axis_rebuilds"] += 1

        # ---- jobs: re-evaluate dirty, version-check the rest ----
        dirty_jobs, self.dirty_jobs = self.dirty_jobs, set()
        cache_jobs = cache.jobs
        job_vers = self.job_vers
        cloned = 0
        for uid in dirty_jobs:
            job = cache_jobs.get(uid)
            if job is None or (job.pod_group is None and job.pdb is None) \
                    or job.queue not in queues:
                self.jobs.pop(uid, None)
                job_vers.pop(uid, None)
                continue
            self._clone_job(cache, job)
            cloned += 1
        for uid, job in list(self.jobs.items()):
            if uid in dirty_jobs:
                continue
            if job._status_version != job_vers[uid] \
                    or uid not in cache_jobs:
                cj = cache_jobs.get(uid)
                if cj is None or (cj.pod_group is None and cj.pdb is None) \
                        or cj.queue not in queues:
                    del self.jobs[uid]
                    del job_vers[uid]
                    continue
                self._clone_job(cache, cj)
                cloned += 1
            elif job.job_fit_errors or job.nodes_fit_errors \
                    or job.nodes_fit_delta:
                # reused clone: per-session scratch must not leak into the
                # next session (fresh clones start empty)
                job.job_fit_errors = ""
                job.nodes_fit_errors = {}
                job.nodes_fit_delta = {}
        self.stats["cloned_jobs"] += cloned
        self.stats["reused_jobs"] += len(self.jobs) - cloned
        return self._emit(cache, queues)

    def _emit(self, cache, queues) -> ClusterInfo:
        """Fresh ClusterInfo over the keeper's live objects: the dicts are
        copies (open_session_state deletes invalid jobs from its dict; the
        keeper's own maps must not see that), the values are shared."""
        snap = ClusterInfo()
        snap.jobs = dict(self.jobs)
        snap.nodes = dict(self.nodes)
        snap.queues = queues
        for ns, coll in cache.namespace_collection.items():
            snap.namespace_info[ns] = coll.snapshot()
        snap.node_axis = self.axis
        return snap
