"""The effector seam of the scheduler cache
(volcano pkg/scheduler/cache/interface.go:27-76).

``Binder``/``Evictor``/``StatusUpdater``/``VolumeBinder`` are the pluggable
write-paths from scheduler decisions back to the state store. Unit tests,
the deterministic replay benchmark, and the TPU parity harness all plug
fakes into exactly this seam.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class Binder(Protocol):
    def bind(self, pod, hostname: str) -> None:
        """Commit a placement (the pods/{name}/binding POST analog)."""


@runtime_checkable
class Evictor(Protocol):
    def evict(self, pod, reason: str = "") -> None:
        """Start graceful deletion of a pod."""


@runtime_checkable
class StatusUpdater(Protocol):
    def update_pod_condition(self, pod, condition) -> None: ...

    def update_pod_group(self, pod_group, status=None) -> None: ...


@runtime_checkable
class VolumeBinder(Protocol):
    def allocate_volumes(self, task, hostname: str) -> None: ...

    def bind_volumes(self, task) -> None: ...
