"""The effector seam of the scheduler cache
(volcano pkg/scheduler/cache/interface.go:27-76).

``Binder``/``Evictor``/``StatusUpdater``/``VolumeBinder`` are the pluggable
write-paths from scheduler decisions back to the state store. Unit tests,
the deterministic replay benchmark, and the TPU parity harness all plug
fakes into exactly this seam.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


class BindManyError(Exception):
    """Raised by a Binder's optional ``bind_many`` on partial failure.

    ``done`` is the count of leading pairs successfully bound before the
    failure, so the caller retries only the remainder instead of re-binding
    pods that already succeeded (which would fail against a real binder and
    spuriously resync genuinely-bound tasks). A bind_many implementation
    that raises anything else promises it made no partial progress."""

    def __init__(self, done: int, cause: Exception):
        super().__init__(f"bind_many failed after {done} binds: {cause}")
        self.done = done
        self.cause = cause


@runtime_checkable
class Binder(Protocol):
    def bind(self, pod, hostname: str) -> None:
        """Commit a placement (the pods/{name}/binding POST analog).

        Implementations may also provide ``bind_many(pairs)`` taking an
        iterable of (pod, hostname); it must raise BindManyError to report
        partial progress."""


@runtime_checkable
class Evictor(Protocol):
    def evict(self, pod, reason: str = "") -> None:
        """Start graceful deletion of a pod."""


@runtime_checkable
class StatusUpdater(Protocol):
    def update_pod_condition(self, pod, condition) -> None: ...

    def update_pod_group(self, pod_group, status=None) -> None: ...


@runtime_checkable
class VolumeBinder(Protocol):
    def allocate_volumes(self, task, hostname: str) -> None: ...

    def bind_volumes(self, task) -> None: ...
