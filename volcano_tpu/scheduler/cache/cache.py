"""SchedulerCache — the cluster mirror the session snapshots from
(volcano pkg/scheduler/cache/{cache.go,event_handlers.go}).

Mirrors the store into JobInfo/NodeInfo/QueueInfo maps via watch streams,
produces the per-session deep-clone ``snapshot()``, and owns the effector
write-path (bind/evict/status) with resync-on-failure.

Differences from the reference, by design:
- watches are synchronous store callbacks, not informer goroutines, so
  ``wait_for_cache_sync`` is trivially true and the whole cache is
  deterministic (a property the replay benchmarks rely on);
- bind/evict call the effector inline rather than in a goroutine; failures
  feed the same ``resync`` path (cache.go:597-613 does this asynchronously).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from volcano_tpu.api import objects
from volcano_tpu.api.cluster_info import ClusterInfo
from volcano_tpu.api.job_info import JobInfo, TaskInfo, new_task_info
from volcano_tpu.api.namespace_info import NamespaceCollection
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.api.unschedule_info import ALL_NODE_UNAVAILABLE
from volcano_tpu.scheduler.cache.interface import BindManyError
from volcano_tpu.store import FencedError, NotFoundError, Store, WatchHandler


def _add_res_vec(res, vec, sign: float, scalar_names) -> None:
    """res += sign * vec over the encoder's resource layout
    (cpu, memory, *scalar_names) — the flush-side twin of the solver's
    apply_delta (ops/solver.py _apply_bulk)."""
    res.milli_cpu += sign * vec[0]
    res.memory += sign * vec[1]
    for si, name in enumerate(scalar_names):
        q = vec[2 + si]
        if q:
            res.add_scalar(name, sign * q)


def _is_terminated(status: TaskStatus) -> bool:
    return status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)


def pod_group_job_id(pg: objects.PodGroup) -> str:
    return f"{pg.metadata.namespace}/{pg.metadata.name}"


# ---------------------------------------------------------------------------
# Default effectors (write back to the store; cache.go:123-260)
# ---------------------------------------------------------------------------


class DefaultBinder:
    """Commit placement by setting spec.node_name (the Bind subresource).

    ``fence_epoch`` stamps every bind with the leadership epoch that
    authorized it (None = fencing off): a deposed leader finishing an
    in-flight fused chain cannot double-bind — the store rejects the
    stale stamp (FencedError) and the failure feeds the ordinary
    resync/rewind machinery. Rejections are counted per instance so the
    failover auditor can balance them against the store's accounting."""

    fence_epoch = None

    def __init__(self, store: Store):
        self.store = store
        self.fenced_rejections = 0

    def bind(self, pod: objects.Pod, hostname: str) -> None:
        pod.spec.node_name = hostname
        try:
            self.store.update(pod, epoch=self.fence_epoch)
        except FencedError:
            self.fenced_rejections += 1
            raise

    def bind_many(self, pairs) -> None:
        """Batch bind; reports partial progress so a mid-batch failure only
        retries the unbound remainder (interface.BindManyError contract)."""
        done = 0
        try:
            for pod, hostname in pairs:
                self.bind(pod, hostname)
                done += 1
        except Exception as e:
            raise BindManyError(done, e) from e


class DefaultEvictor:
    """Graceful deletion: stamp deletion_timestamp; the kubelet analog
    completes the termination. Evictions are fenced exactly like binds —
    a deposed leader must not terminate pods the new leader just placed
    or re-affirmed."""

    fence_epoch = None

    def __init__(self, store: Store):
        self.store = store
        self.fenced_rejections = 0

    def evict(self, pod: objects.Pod, reason: str = "") -> None:
        from volcano_tpu.utils import clock

        pod.metadata.deletion_timestamp = clock.now()
        try:
            self.store.update(pod, epoch=self.fence_epoch)
        except FencedError:
            self.fenced_rejections += 1
            raise


class DefaultStatusUpdater:
    """Status writebacks tolerate deletion races: the snapshot a session
    closes against can be a full cycle stale, and an object deleted in the
    meantime makes its status update moot, not an error — the reference's
    updater logs update failures and moves on (job_updater.go:44-52).
    Fenced rejections are likewise moot-but-counted: a deposed leader's
    close-time condition/status writes must degrade to accounting, not
    crash the close path or overwrite the new leader's truth."""

    fence_epoch = None

    def __init__(self, store: Store):
        self.store = store
        self.fenced_rejections = 0

    def update_pod_condition(self, pod: objects.Pod, condition) -> None:
        for i, c in enumerate(pod.status.conditions):
            if c.type == condition.type:
                pod.status.conditions[i] = condition
                break
        else:
            pod.status.conditions.append(condition)
        try:
            self.store.update(pod, epoch=self.fence_epoch)
        except FencedError:
            self.fenced_rejections += 1
        except NotFoundError:
            pass  # pod deleted since the session snapshot

    def update_pod_group(self, pod_group: objects.PodGroup, status=None) -> None:
        if status is not None:
            # close-time status writeback on the SHARED PodGroup object:
            # the cache and every snapshot clone see it the instant it
            # lands, and the synchronous store echo is recognized by
            # add_pod_group's identity window.
            # vclint: neutral(shared-object status writeback; the echo window owns the mark decision)
            pod_group.status = status
        try:
            self.store.update_status(pod_group, epoch=self.fence_epoch)
        except FencedError:
            self.fenced_rejections += 1
        except NotFoundError:
            pass  # pod group deleted since the session snapshot


class DefaultVolumeBinder:
    """Storeless stand-in: volumes are considered host-agnostic. IS_NOOP
    lets the bulk writeback skip per-task volume calls entirely."""

    IS_NOOP = True

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        task.volume_ready = True

    def bind_volumes(self, task: TaskInfo) -> None:
        pass


class StoreVolumeBinder:
    """PV assume/bind against real PersistentVolume objects — the analog
    of the reference's defaultVolumeBinder wrapping the k8s volumebinder
    (cache.go:240-258): AllocateVolumes ASSUMES a compatible volume for
    each unbound PVC the pod references on the chosen host (raising fails
    the allocation, exactly as an assume failure does), BindVolumes
    commits the assumption (PV/PVC flip to Bound in the store)."""

    def __init__(self, store: Store):
        self.store = store
        # task uid -> [(pvc, pv)] assumed but not yet bound
        self._assumed: Dict[str, list] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _pvc_names(task: TaskInfo) -> list:
        pod = task.pod
        if pod is None:
            return []
        return [v.persistent_volume_claim for v in pod.spec.volumes
                if v.persistent_volume_claim]

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        names = self._pvc_names(task)
        if not names:
            task.volume_ready = True
            return
        from volcano_tpu.api.quantity import parse_quantity

        assumed = []
        with self._lock:
            taken = {pv.metadata.name for lst in self._assumed.values()
                     for _, pv in lst}
            for name in names:
                pvc = self.store.try_get(
                    "PersistentVolumeClaim", task.namespace, name)
                if pvc is None:
                    raise RuntimeError(
                        f"pvc {task.namespace}/{name} not found")
                if pvc.phase == "Bound":
                    # a bound volume constrains placement: the host must
                    # satisfy the volume's node affinity
                    pv = self.store.try_get(
                        "PersistentVolume", "", pvc.volume_name)
                    if pv is not None and pv.node_names \
                            and hostname not in pv.node_names:
                        raise RuntimeError(
                            f"pvc {task.namespace}/{name} is bound to "
                            f"volume {pv.metadata.name} not reachable from "
                            f"{hostname}")
                    continue
                want = parse_quantity(pvc.requests.get("storage", 0))
                best = None
                for pv in self.store.list("PersistentVolume"):
                    if pv.phase != "Available" or pv.claim_ref:
                        continue
                    if pv.metadata.name in taken:
                        continue
                    if pv.node_names and hostname not in pv.node_names:
                        continue
                    have = parse_quantity(pv.capacity.get("storage", 0))
                    if have < want:
                        continue
                    # smallest sufficient volume, name tie-break — the
                    # k8s binder's smallest-fit policy, deterministic
                    key = (have, pv.metadata.name)
                    if best is None or key < (best[0], best[1].metadata.name):
                        best = (have, pv)
                if best is None:
                    raise RuntimeError(
                        f"no PersistentVolume fits pvc "
                        f"{task.namespace}/{name} on {hostname}")
                taken.add(best[1].metadata.name)
                assumed.append((pvc, best[1]))
            if assumed:
                self._assumed.setdefault(task.uid, []).extend(assumed)
        task.volume_ready = True

    def bind_volumes(self, task: TaskInfo) -> None:
        with self._lock:
            assumed = self._assumed.pop(task.uid, [])
        for pvc, pv in assumed:
            pv.claim_ref = f"{pvc.metadata.namespace}/{pvc.metadata.name}"
            pv.phase = "Bound"
            pvc.phase = "Bound"
            pvc.volume_name = pv.metadata.name
            self.store.update_status(pv)
            self.store.update_status(pvc)

    def unassume(self, task: TaskInfo) -> None:
        """Release assumptions for a task whose placement was discarded
        (statement rollback); bound volumes are untouched."""
        with self._lock:
            self._assumed.pop(task.uid, None)

    def reset_assumptions(self) -> None:
        """Session close: drop every unbound assumption — assume/bind
        always completes within one session (dispatch or statement
        commit), so leftovers belong to placements that never dispatched
        and would otherwise pin their PVs forever."""
        with self._lock:
            self._assumed.clear()


# ---------------------------------------------------------------------------
# The cache
# ---------------------------------------------------------------------------


class SchedulerCache:
    def __init__(
        self,
        store: Optional[Store] = None,
        scheduler_name: str = "volcano",
        default_queue: str = "default",
        binder=None,
        evictor=None,
        status_updater=None,
        volume_binder=None,
    ):
        self.store = store
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue

        self.binder = binder if binder is not None else (DefaultBinder(store) if store else None)
        self.evictor = evictor if evictor is not None else (DefaultEvictor(store) if store else None)
        self.status_updater = (
            status_updater if status_updater is not None else (DefaultStatusUpdater(store) if store else None)
        )
        self.volume_binder = (
            volume_binder if volume_binder is not None
            else (StoreVolumeBinder(store) if store else DefaultVolumeBinder()))

        from volcano_tpu.scheduler.cache.podtable import PodTable
        from volcano_tpu.scheduler.cache.snapkeeper import SnapshotKeeper

        self.pod_table = PodTable()
        # delta-maintained session snapshot (snapkeeper.py): watch/effector
        # mutation paths below mark the touched job/node so snapshot()
        # re-clones only what moved since the last session
        self.snap_keeper = SnapshotKeeper()
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, objects.PriorityClass] = {}
        self.default_priority = 0
        self.namespace_collection: Dict[str, NamespaceCollection] = {}

        self._lock = threading.RLock()
        # pods referencing PVCs (bulk-apply volume-call gate: a session
        # with none skips per-task volume work entirely)
        self._pvc_pod_count = 0
        self._err_tasks: List[TaskInfo] = []
        self._deleted_jobs: List[JobInfo] = []
        # native mirror-transition ctx for the effector path (built lazily;
        # False = not attempted, None = unavailable). jobs/nodes dict
        # objects are created once above and never reassigned, so the ctx
        # stays valid for the cache's lifetime.
        self._fast_mirror = False
        # deferred bulk-writeback payloads (ops/solver.py _apply_bulk): the
        # cache-side half of a session's placements, applied at session
        # close / before the next snapshot — the reference's Bind is async
        # and its cache learns statuses from later watch events, so the
        # mirror being one flush behind inside a cycle is the faithful
        # semantic (cache.go:123-135,597-613)
        self._pending_mirrors: List[dict] = []
        # express lane (volcano_tpu/express): the lane registers itself
        # plus an arrival listener; the listener runs under the cache lock
        # from the watch handlers and must only enqueue
        self.express_lane = None
        self._arrival_listener = None
        # lease-epoch fencing (store/store.py): the epoch stamped onto
        # every effector write of the current leadership term, and the
        # count of writes the store rejected as stale (split-brain
        # attempts that the fence turned into ordinary effector failures)
        self.fence_epoch = None
        self.fenced_writes = 0
        # a new leadership term owes the cluster one recovery sweep: the
        # first session after set_fence_epoch reverts any half-bound gang
        # a deposed leader's fenced mid-chain abort left in the store
        # (framework.run_actions consumes this flag)
        self.fence_sweep_due = False
        # continuous pipeline (volcano_tpu/pipeline): when armed, every
        # snapshot() alternates the keeper's double buffer so consecutive
        # sessions never share clone objects (cycle N's close can still
        # read its snapshot while cycle N+1's is already solving)
        self._pipeline_swap = False
        # self-echo window (update_job_status): the in-process store
        # dispatches watch callbacks synchronously with the SAME object the
        # writer handed it, so the close-time PodGroup status writeback
        # comes straight back through update_pod_group_from_watch. The
        # mutation already happened on the shared object before the write —
        # marking the job again only churns the dirty-set (and, in pipeline
        # mode, spuriously invalidates every speculative solve-ahead).
        # RemoteStore echoes deserialize to a different object and keep the
        # full mark path.
        self._expect_pg_echo = None

    def set_fence_epoch(self, epoch) -> None:
        """Stamp this cache's effector write-path with a leadership epoch
        (None disarms). Called on lease acquisition BEFORE the session
        loop starts, and deliberately NOT on loss — a deposed term's
        in-flight writes must keep their stale stamp so the store fences
        them, instead of regressing to unfenced authority."""
        self.fence_epoch = epoch
        self.fence_sweep_due = epoch is not None
        for effector in (self.binder, self.evictor, self.status_updater):
            if effector is not None and hasattr(effector, "fence_epoch"):
                effector.fence_epoch = epoch

    def fenced_rejections(self) -> int:
        """Fenced-write rejections observed through this cache's effectors
        plus the bulk-writeback path (the auditor's balance probe)."""
        total = self.fenced_writes
        for effector in (self.binder, self.evictor, self.status_updater):
            total += getattr(effector, "fenced_rejections", 0)
        return total

    def set_arrival_listener(self, fn) -> None:
        """Register the express lane's arrival callback: fn(job_uid) is
        invoked (under the cache lock) whenever a schedulable pending task
        or a PodGroup lands — mirror + enqueue only, by contract."""
        self._arrival_listener = fn

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> None:
        """Wire the 11-informer equivalent: watch every kind the scheduler
        consumes (cache.go:322-425). Idempotent — the scheduler driver and
        an embedding cluster may both call it."""
        if self.store is None or getattr(self, "_watching", False):
            return
        self._watching = True
        s = self.store
        self._watch_regs = [
            ("Pod", WatchHandler(self.add_pod, self.update_pod_from_watch, self.delete_pod)),
            ("Node", WatchHandler(self.add_node, self.update_node_from_watch, self.delete_node)),
            ("PodGroup", WatchHandler(self.add_pod_group, self.update_pod_group_from_watch, self.delete_pod_group)),
            ("Queue", WatchHandler(self.add_queue, self.update_queue_from_watch, self.delete_queue)),
            ("PriorityClass", WatchHandler(self.add_priority_class, self.update_priority_class_from_watch, self.delete_priority_class)),
            ("ResourceQuota", WatchHandler(self.add_resource_quota, self.update_resource_quota_from_watch, self.delete_resource_quota)),
            ("PodDisruptionBudget", WatchHandler(self.add_pdb, self.update_pdb_from_watch, self.delete_pdb)),
        ]
        for kind, handler in self._watch_regs:
            s.watch(kind, handler)

    def detach_watches(self) -> None:
        """Unregister this cache's store watches (sim restart-injection /
        teardown): a replacement cache can then run() against the same
        store without the old cache double-mirroring every write."""
        if self.store is None or not getattr(self, "_watching", False):
            return
        for kind, handler in getattr(self, "_watch_regs", []):
            self.store.unwatch(kind, handler)
        self._watch_regs = []
        self._watching = False

    def wait_for_cache_sync(self) -> bool:
        return True  # synchronous watches are always synced

    # -- pod/task handlers (event_handlers.go:39-200) ----------------------

    def _get_or_create_job(self, ti: TaskInfo) -> Optional[JobInfo]:
        if not ti.job:
            return None
        if ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    def _add_task(self, ti: TaskInfo) -> None:
        self.snap_keeper.mark_job(ti.job)
        self.snap_keeper.mark_node(ti.node_name)
        job = self._get_or_create_job(ti)
        if job is not None:
            job.add_task_info(ti)
        if ti.pod is not None and any(
                v.persistent_volume_claim for v in ti.pod.spec.volumes):
            self._pvc_pod_count += 1
        if ti.pod is not None:
            # columnar mirror row (podtable.py): the encoder gathers dense
            # arrays instead of walking 50k task objects per session
            self.pod_table.add(ti.pod, ti)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                self.nodes[ti.node_name] = NodeInfo(None)
            if not _is_terminated(ti.status):
                self.nodes[ti.node_name].add_task(ti)
        elif ti.status == TaskStatus.PENDING and ti.job \
                and self._arrival_listener is not None:
            self._arrival_listener(ti.job)

    def _delete_task(self, ti: TaskInfo) -> None:
        self.snap_keeper.mark_job(ti.job)
        self.snap_keeper.mark_node(ti.node_name)
        if ti.pod is not None and any(
                v.persistent_volume_claim for v in ti.pod.spec.volumes):
            self._pvc_pod_count = max(0, self._pvc_pod_count - 1)
        self.pod_table.remove(ti.uid)
        errs = []
        if ti.job:
            job = self.jobs.get(ti.job)
            if job is not None:
                try:
                    job.delete_task_info(ti)
                except KeyError as e:
                    errs.append(e)
            else:
                errs.append(KeyError(f"failed to find Job {ti.job} for task {ti.namespace}/{ti.name}"))
        if ti.node_name:
            node = self.nodes.get(ti.node_name)
            if node is not None:
                try:
                    node.remove_task(ti)
                except RuntimeError as e:
                    errs.append(e)
        if errs:
            raise RuntimeError("; ".join(str(e) for e in errs))

    def _responsible_for(self, pod: objects.Pod) -> bool:
        """Informer filter (cache.go:352-361): our pods, plus ANY bound pod —
        foreign bound pods must still count against node resources."""
        return (
            pod.spec.scheduler_name == self.scheduler_name
            or bool(pod.metadata.annotations.get(objects.GROUP_NAME_ANNOTATION_KEY))
            or bool(pod.spec.node_name)
        )

    def add_pod(self, pod: objects.Pod) -> None:
        self.flush_mirror()  # watch updates must land on a flushed mirror
        with self._lock:
            if not self._responsible_for(pod):
                return
            self._add_task(new_task_info(pod))

    def update_pod_from_watch(self, old_pod: objects.Pod, new_pod: objects.Pod) -> None:
        self.flush_mirror()  # see add_pod
        with self._lock:
            if old_pod is new_pod and self._neutral_pod_echo(new_pod):
                # a same-object write (in-process store dispatches the
                # writer's object) whose scheduling-relevant derived state
                # matches the cached task: a condition/metadata-only echo
                # — typically our own close-time FailedScheduling
                # writeback. Resyncing would rebuild an equal TaskInfo and
                # re-mark its job/node for nothing (in pipeline mode that
                # mark spuriously discards the speculative solve-ahead).
                # Bind confirmations and kubelet phase flips change the
                # derived status and keep the full resync path.
                return
            self._delete_pod_locked(old_pod)
            if not self._responsible_for(new_pod):
                return
            self._add_task(new_task_info(new_pod))

    def _neutral_pod_echo(self, pod: objects.Pod) -> bool:
        """True when the cached task for ``pod`` already matches the
        pod-derived scheduling state (status + node), so a same-object
        update carries nothing the scheduler can observe. Requests are not
        compared: the pod IS the cached task's pod object, and spec
        resources deriving resreq are immutable post-admission."""
        if not self._responsible_for(pod):
            return False
        pi = new_task_info(pod)
        job = self.jobs.get(pi.job)
        task = job.tasks.get(pi.uid) if job is not None else None
        if task is None or task.pod is not pod:
            return False
        return (task.status == pi.status
                and (task.node_name or "") == (pi.node_name or ""))

    def _delete_pod_locked(self, pod: objects.Pod) -> None:
        pi = new_task_info(pod)
        # Prefer the cached task (it may be in Binding status; event_handlers.go:154-161)
        task = pi
        job = self.jobs.get(pi.job)
        if job is not None and pi.uid in job.tasks:
            task = job.tasks[pi.uid]
        try:
            self._delete_task(task)
        except RuntimeError:
            pass
        if job is not None and job.is_terminated():
            self._delete_job(job)

    def delete_pod(self, pod: objects.Pod) -> None:
        self.flush_mirror()  # see add_pod
        with self._lock:
            self._delete_pod_locked(pod)

    # -- node handlers -----------------------------------------------------

    def add_node(self, node: objects.Node) -> None:
        self.flush_mirror()  # deferred node deltas must precede a set_node/rebuild
        with self._lock:
            self.snap_keeper.mark_node(node.metadata.name)
            if node.metadata.name in self.nodes:
                self.nodes[node.metadata.name].set_node(node)
            else:
                self.nodes[node.metadata.name] = NodeInfo(node)

    def update_node_from_watch(self, old: objects.Node, new: objects.Node) -> None:
        self.add_node(new)

    def delete_node(self, node: objects.Node) -> None:
        self.flush_mirror()  # see add_node
        with self._lock:
            self.snap_keeper.mark_node(node.metadata.name)
            self.nodes.pop(node.metadata.name, None)

    # -- podgroup handlers (event_handlers.go:159-196) ---------------------

    def add_pod_group(self, pg: objects.PodGroup) -> None:
        with self._lock:
            job_id = pod_group_job_id(pg)
            job = self.jobs.get(job_id)
            if pg is self._expect_pg_echo and job is not None \
                    and job.pod_group is pg:
                # our own status writeback echoing back as the identical
                # object: the cache (and every snapshot clone, which
                # shares pod_group) already sees the mutation — re-marking
                # would only dirty the keeper for a value-neutral event.
                # set_pod_group still runs: it re-reads derived fields
                # from the same object (idempotent, cheap).
                # vclint: neutral(same-object echo of our own writeback; value already visible to cache and clones - RemoteStore echoes keep the full mark path)
                job.set_pod_group(pg)
                return
            self.snap_keeper.mark_job(job_id)
            if job_id not in self.jobs:
                self.jobs[job_id] = JobInfo(job_id)
            job = self.jobs[job_id]
            job.set_pod_group(pg)
            if not job.queue:
                job.queue = self.default_queue
            if self._arrival_listener is not None:
                # a group admitted after its pods arrived completes the
                # express eligibility picture — re-nudge the lane
                self._arrival_listener(job_id)

    def update_pod_group_from_watch(self, old: objects.PodGroup, new: objects.PodGroup) -> None:
        self.add_pod_group(new)

    def delete_pod_group(self, pg: objects.PodGroup) -> None:
        self.flush_mirror()  # job deletion must see flushed task state
        with self._lock:
            job_id = pod_group_job_id(pg)
            self.snap_keeper.mark_job(job_id)
            job = self.jobs.get(job_id)
            if job is None:
                return
            job.unset_pod_group()
            self._delete_job(job)

    # -- queue handlers ----------------------------------------------------

    def add_queue(self, queue: objects.Queue) -> None:
        with self._lock:
            if queue.metadata.name not in self.queues:
                # queue SET changes flip job eligibility cluster-wide;
                # updates of an existing queue don't (QueueInfos are
                # re-cloned fresh every snapshot regardless)
                self.snap_keeper.invalidate()
            else:
                # spec updates (weight, capability) re-derive fresh next
                # snapshot, but a speculative solve sealed under the old
                # policy must be invalidated (snapkeeper.mark_meta) —
                # scoped to the queue so the read-set intersect can let
                # noise on a queue the sealed solve never consumed commit
                self.snap_keeper.mark_meta("queue", queue.metadata.name)
            self.queues[queue.metadata.name] = QueueInfo(queue)

    def update_queue_from_watch(self, old: objects.Queue, new: objects.Queue) -> None:
        self.add_queue(new)

    def delete_queue(self, queue: objects.Queue) -> None:
        with self._lock:
            # pop only a queue we actually hold, on the same path as its
            # invalidation — a delete for an unknown queue must neither
            # mutate nor rebuild (VT007: every mutation reaches a mark)
            if queue.metadata.name in self.queues:
                self.snap_keeper.invalidate()
                self.queues.pop(queue.metadata.name, None)

    # -- priority class handlers (event_handlers.go) -----------------------

    def add_priority_class(self, pc: objects.PriorityClass) -> None:
        with self._lock:
            # job.priority derives from the PC set at snapshot time; the
            # dirty-sets don't model that dependency, so rebuild wholesale
            self.snap_keeper.invalidate()
            self.priority_classes[pc.metadata.name] = pc
            if pc.global_default:
                self.default_priority = pc.value

    def update_priority_class_from_watch(self, old, new) -> None:
        self.add_priority_class(new)

    def delete_priority_class(self, pc: objects.PriorityClass) -> None:
        with self._lock:
            self.snap_keeper.invalidate()
            self.priority_classes.pop(pc.metadata.name, None)
            if pc.global_default:
                self.default_priority = 0

    # -- resource quota handlers (namespace weights) -----------------------

    def add_resource_quota(self, quota: objects.ResourceQuota) -> None:
        with self._lock:
            ns = quota.metadata.namespace
            coll = self.namespace_collection.setdefault(ns, NamespaceCollection(ns))
            coll.update(quota)
            # namespace weights re-derive fresh each snapshot; the epoch
            # bump invalidates any speculative solve sealed under the
            # old weights (snapkeeper.mark_meta), scoped to the namespace
            self.snap_keeper.mark_meta("quota", ns)

    def update_resource_quota_from_watch(self, old, new) -> None:
        self.add_resource_quota(new)

    def delete_resource_quota(self, quota: objects.ResourceQuota) -> None:
        with self._lock:
            coll = self.namespace_collection.get(quota.metadata.namespace)
            if coll is not None:
                coll.delete(quota)
                if coll.empty():
                    del self.namespace_collection[quota.metadata.namespace]
                self.snap_keeper.mark_meta("quota", quota.metadata.namespace)

    # -- pdb handlers ------------------------------------------------------

    def add_pdb(self, pdb: objects.PodDisruptionBudget) -> None:
        with self._lock:
            job_id = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
            self.snap_keeper.mark_job(job_id)
            if job_id not in self.jobs:
                self.jobs[job_id] = JobInfo(job_id)
            self.jobs[job_id].set_pdb(pdb)

    def update_pdb_from_watch(self, old, new) -> None:
        self.add_pdb(new)

    def delete_pdb(self, pdb: objects.PodDisruptionBudget) -> None:
        with self._lock:
            job_id = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
            self.snap_keeper.mark_job(job_id)
            job = self.jobs.get(job_id)
            if job is None:
                return
            job.unset_pdb()
            self._delete_job(job)

    # -- job cleanup (cache.go:656-688) ------------------------------------

    def _delete_job(self, job: JobInfo) -> None:
        self.snap_keeper.mark_job(job.uid)
        self._deleted_jobs.append(job)
        self._process_cleanup_jobs()

    def _process_cleanup_jobs(self) -> None:
        remaining = []
        for job in self._deleted_jobs:
            if job.is_terminated():
                self.jobs.pop(job.uid, None)
            else:
                remaining.append(job)
        self._deleted_jobs = remaining

    # -- effector path (cache.go:499-613) ----------------------------------

    def _find_job_and_task(self, task_info: TaskInfo):
        job = self.jobs.get(task_info.job)
        if job is None:
            raise KeyError(f"failed to find Job {task_info.job} for Task {task_info.uid}")
        task = job.tasks.get(task_info.uid)
        if task is None:
            raise KeyError(f"failed to find task in status {task_info.status} by id {task_info.uid}")
        return job, task

    def _mirror(self):
        """Native effector-side transition ctx, or None (Python path). A
        None while the background native compile is still in flight is NOT
        latched — the cache outlives sessions, so giving up on the first
        cold-start call would disable the native path for its lifetime."""
        if self._fast_mirror is False:
            from volcano_tpu.ops import fasttrans

            m = fasttrans.build_mirror(self.jobs, self.nodes)
            if m is None and not fasttrans.native_settled():
                return None  # retry on a later effector call
            self._fast_mirror = m
        return self._fast_mirror

    def bind(self, task_info: TaskInfo, hostname: str) -> None:
        """Update cache state to Binding and invoke the binder; on binder
        failure, queue the task for resync (cache.go:558-613)."""
        mirror = self._mirror()
        with self._lock:
            self.snap_keeper.mark_job(task_info.job)
            self.snap_keeper.mark_node(hostname)
            if mirror is not None:
                task, pod = mirror.mirror_bind(task_info, hostname)
            else:
                job, task = self._find_job_and_task(task_info)
                node = self.nodes.get(hostname)
                if node is None:
                    raise KeyError(f"failed to bind Task {task.uid} to host {hostname}: host does not exist")
                job.update_task_status(task, TaskStatus.BINDING)
                task.node_name = hostname
                node.add_task(task)
                pod = task.pod
        try:
            self.binder.bind(pod, hostname)
        except FencedError:
            # deposed leadership: undo the cache-side flip via resync and
            # RE-RAISE so batch callers (express commit) stop dispatching
            # the rest of a doomed gang instead of burning one rejection
            # per task — per-task callers (Statement commit) already treat
            # a bind failure as non-fatal
            self.resync_task(task)
            raise
        except Exception:
            self.resync_task(task)
        else:
            if self.store is not None:
                self.store.record_event(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.metadata.namespace}/{pod.metadata.name} to {hostname}",
                )

    def evict(self, task_info: TaskInfo, reason: str) -> None:
        mirror = self._mirror()
        with self._lock:
            self.snap_keeper.mark_evict(task_info.job, task_info.node_name)
            if mirror is not None:
                task, pod = mirror.mirror_evict(task_info)
            else:
                job, task = self._find_job_and_task(task_info)
                node = self.nodes.get(task.node_name)
                if node is None:
                    raise KeyError(f"failed to evict Task {task.uid}: host {task.node_name} does not exist")
                job.update_task_status(task, TaskStatus.RELEASING)
                node.update_task(task)
                pod = task.pod
        try:
            self.evictor.evict(pod, reason)
        except FencedError:
            self.resync_task(task)
            raise  # see bind(): deposed leadership stops the batch
        except Exception:
            self.resync_task(task)
        else:
            if self.store is not None:
                self.store.record_event(pod, "Normal", "Evict", reason)

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)

    # -- resync (cache.go:688-710, event_handlers.go:88-105) ---------------

    def resync_task(self, task: TaskInfo) -> None:
        self._err_tasks.append(task)

    def process_resync_tasks(self) -> None:
        """Re-fetch truth from the store for tasks whose effector failed."""
        self.flush_mirror()  # sync_task deletes/re-adds against the mirror
        tasks, self._err_tasks = self._err_tasks, []
        for task in tasks:
            try:
                self.sync_task(task)
            except Exception:
                self._err_tasks.append(task)

    def sync_task(self, old_task: TaskInfo) -> None:
        if self.store is None:
            return
        try:
            new_pod = self.store.get("Pod", old_task.namespace, old_task.name)
        except NotFoundError:
            with self._lock:
                try:
                    self._delete_task(old_task)
                except RuntimeError:
                    pass
            return
        with self._lock:
            self._delete_task(old_task)
            self._add_task(new_task_info(new_pod))

    # -- status writeback (cache.go:832-895) -------------------------------

    def task_unschedulable(self, task: TaskInfo, message: str) -> None:
        """Record FailedScheduling + update the PodScheduled condition
        (cache.go:629-655), deduping unchanged conditions."""
        pod = task.pod
        condition = objects.PodCondition(
            type="PodScheduled", status="False", reason="Unschedulable", message=message
        )
        for c in pod.status.conditions:
            if (
                c.type == condition.type
                and c.status == condition.status
                and c.message == condition.message
            ):
                return  # no update needed
        if self.store is not None:
            self.store.record_event(pod, "Warning", "FailedScheduling", message)
        if self.status_updater is not None:
            self.status_updater.update_pod_condition(pod, condition)

    def record_job_status_event(self, job: JobInfo) -> None:
        """(cache.go:834-869)"""
        base_msg = job.job_fit_errors or ALL_NODE_UNAVAILABLE
        pg_unschedulable = job.pod_group is not None and job.pod_group.status.phase in (
            objects.PodGroupPhase.UNKNOWN,
            objects.PodGroupPhase.PENDING,
            objects.PodGroupPhase.INQUEUE,
        )
        pdb_unschedulable = job.pdb is not None and bool(
            job.task_status_index.get(TaskStatus.PENDING)
        )
        if (pg_unschedulable or pdb_unschedulable) and self.store is not None and job.pod_group is not None:
            pending = len(job.task_status_index.get(TaskStatus.PENDING, {}))
            msg = f"{pending}/{len(job.tasks)} tasks in gang unschedulable: {job.fit_error()}"
            self.store.record_event(job.pod_group, "Warning", "Unschedulable", msg)

        for status in (TaskStatus.ALLOCATED, TaskStatus.PENDING, TaskStatus.PIPELINED):
            for task in job.task_status_index.get(status, {}).values():
                fit_error = job.nodes_fit_errors.get(task.uid)
                msg = fit_error.error() if fit_error is not None else base_msg
                self.task_unschedulable(task, msg)

    def update_job_status(self, job: JobInfo, update_pg: bool) -> JobInfo:
        if update_pg and self.status_updater is not None and job.pod_group is not None:
            # the synchronous in-process echo of this write is value-
            # neutral (the status swap already landed on the shared
            # object); the identity window lets add_pod_group recognize it
            # and skip the spurious keeper mark
            self._expect_pg_echo = job.pod_group
            try:
                self.status_updater.update_pod_group(job.pod_group)
            finally:
                self._expect_pg_echo = None
        self.record_job_status_event(job)
        return job

    # -- snapshot (cache.go:713-798) ---------------------------------------

    def defer_mirror(self, payload: dict) -> None:
        """Queue the cache-side half of a bulk writeback (see _apply_bulk);
        applied by flush_mirror before anything reads the mirror."""
        with self._lock:
            self._pending_mirrors.append(payload)

    def flush_mirror(self) -> None:
        """Apply deferred bulk-writeback payloads to the cache trees:
        status flips + bucket moves + node task-map inserts + allocated /
        idle / used sums for every placement the session's bulk apply
        performed. Runs entirely under the cache lock (the same discipline
        as the effectors and watch handlers). Ordering with interleaved
        effector calls is safe: bulk-bound tasks are disjoint from the
        tasks bind/evict touch, and the node deltas here move idle/used
        while evictions move releasing.

        Accounting is PER FLIPPED TASK on both the job AND node side: a
        placed task whose cache twin vanished in the defer window (pod
        deleted) contributes nothing here — its sums were settled by
        delete_task_info — so node idle/used never drifts from the
        sum-over-held-tasks invariant the incremental snapshot relies on.
        After an exact flush the cache twins equal the session objects, so
        the snapshot keeper records them as in-sync (the payload carries
        the session-side versions captured at defer time); any skipped
        task re-dirties its job and node instead."""
        with self._lock:
            pending, self._pending_mirrors = self._pending_mirrors, []
            if not pending:
                return
            BINDING = TaskStatus.BINDING
            keeper = self.snap_keeper
            # native batched flush (fastapply.c mirror_all_jobs /
            # apply_node_deltas): identical semantics to the Python body
            # below, which remains the fallback and oracle. Non-blocking —
            # a cold process flushes through the Python loop rather than
            # waiting on the background cc.
            from volcano_tpu._native import get_fastapply_nowait

            mod = get_fastapply_nowait()
            mirror_all = getattr(mod, "mirror_all_jobs", None) \
                if mod is not None else None
            alloc_mask = (int(TaskStatus.BOUND) | int(TaskStatus.BINDING)
                          | int(TaskStatus.RUNNING)
                          | int(TaskStatus.ALLOCATED))
            for p in pending:
                task_infos = p["task_infos"]
                node_names = p["node_names"]
                scalar_names = p["scalar_names"]
                skipped: List[int] = []
                if mirror_all is not None:
                    skipped = mirror_all(
                        p["job_nz"], p["seg_ends"], p["placed"],
                        p["assign"].astype(np.int64, copy=False),
                        task_infos, node_names, self.nodes,
                        p["job_infos"], self.jobs,
                        TaskStatus.PENDING, BINDING,
                        np.ascontiguousarray(p["job_sums"]),
                        tuple(scalar_names), alloc_mask) or []
                else:
                    assign = p["assign"]
                    placed = p["placed"].tolist()
                    lo = 0
                    for ji, hi in zip(p["job_nz"].tolist(),
                                      p["seg_ends"].tolist()):
                        tis = placed[lo:hi]
                        seg_lo = lo
                        lo = hi
                        job = p["job_infos"][ji]
                        cache_job = self.jobs.get(job.uid)
                        if cache_job is None:
                            skipped.extend(range(seg_lo, hi))
                            continue
                        cache_job._status_version += 1
                        cidx = cache_job.task_status_index
                        c_tasks = cache_job.tasks
                        for k, ti in enumerate(tis, start=seg_lo):
                            task = task_infos[ti]
                            ctask = c_tasks.get(task.uid)
                            if ctask is None:
                                # the pod was deleted in the defer window;
                                # delete_task_info settled its sums
                                skipped.append(k)
                                continue
                            host = node_names[int(assign[ti])]
                            old_status = ctask.status
                            old_bucket = cidx.get(old_status)
                            if old_bucket is not None:
                                old_bucket.pop(ctask.uid, None)
                                if not old_bucket:
                                    del cidx[old_status]
                            ctask.node_name = host
                            ctask.status = BINDING
                            cidx.setdefault(BINDING, {})[ctask.uid] = ctask
                            # per-flipped-task boundary rules, exactly as
                            # update_task_status moves the sums
                            if not allocated_status(old_status):
                                cache_job.allocated.add(ctask.resreq)
                            if old_status == TaskStatus.PENDING:
                                cache_job.pending_sum.sub(ctask.resreq)
                            cnode = self.nodes.get(host)
                            if cnode is not None:
                                cnode._acct_gen += 1
                                # the session task is shared into the cache
                                # node map, as the inline writeback did
                                cnode.tasks[task.key] = task
                self._flush_node_deltas(p, skipped, mod)
                self._flush_sync_keeper(p, skipped, keeper)

    def _flush_node_deltas(self, p: dict, skipped: List[int], mod) -> None:
        """Node idle/used deltas for one payload, restricted to the tasks
        the mirror pass actually flipped: skipped placements (cache twin
        deleted in the defer window) are subtracted from the session's
        wholesale per-node sums before they land on the cache nodes."""
        node_names = p["node_names"]
        scalar_names = p["scalar_names"]
        node_sums = p["node_sums"]
        if skipped:
            placed_req = p.get("placed_req")
            if placed_req is not None:
                node_sums = node_sums.copy()
                placed = p["placed"]
                assign = p["assign"]
                for k in skipped:
                    node_sums[int(assign[int(placed[k])])] -= placed_req[k]
            # else: a legacy payload without per-task reqs; the wholesale
            # sums are applied and the touched nodes are re-cloned next
            # open anyway (skipped marks them dirty below)
        fast_nodes = getattr(mod, "apply_node_deltas", None) \
            if mod is not None else None
        if fast_nodes is not None:
            fast_nodes(p["node_nz"], np.ascontiguousarray(node_sums),
                       node_names, self.nodes, None, tuple(scalar_names))
            return
        sums = node_sums.tolist()
        for ni in p["node_nz"].tolist():
            cnode = self.nodes.get(node_names[ni])
            if cnode is None:
                continue
            cnode._acct_gen += 1
            vec = sums[ni]
            _add_res_vec(cnode.idle, vec, -1.0, scalar_names)
            _add_res_vec(cnode.used, vec, +1.0, scalar_names)

    def _flush_sync_keeper(self, p: dict, skipped: List[int],
                           keeper) -> None:
        """Record the flushed objects as snapshot-in-sync (versions were
        captured at defer time, AFTER the session-side bulk mutations), so
        the next open reuses them; skipped placements re-dirty instead."""
        job_vers = p.get("job_vers")
        if job_vers is not None:
            job_infos = p["job_infos"]
            for ji, ver in zip(p["job_nz"].tolist(), job_vers):
                keeper.sync_job(job_infos[ji].uid, ver)
        node_gens = p.get("node_gens")
        if node_gens is not None:
            node_names = p["node_names"]
            for ni, gen in zip(p["node_nz"].tolist(), node_gens):
                keeper.sync_node(node_names[ni], gen)
        if skipped:
            task_infos = p["task_infos"]
            node_names = p["node_names"]
            placed = p["placed"]
            assign = p["assign"]
            for k in skipped:
                ti = int(placed[k])
                keeper.mark_job(task_infos[ti].job)
                keeper.mark_node(node_names[int(assign[ti])])

    def snapshot(self) -> ClusterInfo:
        """The per-session snapshot, delta-maintained by the keeper
        (snapkeeper.py): only jobs/nodes whose cache twins or handed-out
        clones moved since the last session are re-cloned; the first call
        (and any keeper invalidation) is the wholesale rebuild of
        cache.go:713-798. In pipeline mode the keeper's buffer pair is
        swapped first — the flush lands on the PREVIOUS session's buffer
        (whose objects the flush mirrored), then the other buffer is
        delta-opened for the new session."""
        self.flush_mirror()
        with self._lock:
            if self._pipeline_swap:
                self.snap_keeper.swap()
            return self.snap_keeper.snapshot(self)

    # -- continuous pipeline support (volcano_tpu/pipeline) ----------------

    def enable_pipeline(self) -> None:
        """Arm the double-buffered snapshot path (idempotent). Serial
        callers are untouched until this is called; VOLCANO_TPU_PIPELINE=0
        keeps the single-buffer oracle by never calling it."""
        self.snap_keeper.enable_pair()
        self._pipeline_swap = True

    def pipeline_fingerprint(self) -> tuple:
        """The delta fingerprint a speculative solve-ahead seals at
        dispatch and re-checks before apply: the keeper's dirty epoch
        (every watch/effector mark bumps it), the keeper generation
        (wholesale invalidations), the lease fence epoch (a takeover must
        kill in-flight speculation), and the summed cache-node accounting
        generation plus the summed job status version (belt-and-braces
        for any mirror mutation a mark path missed — the job sum is the
        node sum's twin: without it an unmarked job-side mutation would
        move neither dirty epoch nor acct and a sealed stage could commit
        against state it never saw; surfaced by vclint VT009). Any
        component moving between seal and check means state the
        speculative snapshot did not see — the stage is discarded. The
        device replica's epoch (ops/replica.py) rides along: a sealed
        stage captured its staged buffers from a specific replica state,
        and a scatter/rebuild/adoption between seal and check means the
        device content it dispatched against has been superseded."""
        keeper = self.snap_keeper
        rep = getattr(self, "_device_replica", None)
        with self._lock:
            acct = 0
            for node in self.nodes.values():
                acct += node._acct_gen
            jver = 0
            for job in self.jobs.values():
                jver += job._status_version
            return (keeper.dirty_epoch, keeper.generation,
                    self.fence_epoch, acct, len(self.nodes),
                    jver, len(self.jobs),
                    rep.replica_epoch if rep is not None else -1)

    def readset_seal(self) -> dict:
        """Capture the read-set seal baseline for a speculative dispatch
        (read-set-scoped invalidation, pipeline/driver.py): the mark
        journal cursor (dirty_epoch; the journal is armed here on first
        use), per-row version baselines for every node and job, and the
        queue/namespace id sets the sealed snapshot could have consumed.
        One locked O(N+J) pass — the same complexity class as the
        fingerprint itself, taken at the same moment so the cursor and
        the baselines describe one consistent state."""
        with self._lock:
            keeper = self.snap_keeper
            keeper.enable_journal()
            return {
                "cursor": keeper.dirty_epoch,
                "node_gens": {name: node._acct_gen
                              for name, node in self.nodes.items()},
                "job_vers": {uid: job._status_version
                             for uid, job in self.jobs.items()},
                "jobs": set(self.jobs.keys()),
                "queues": set(self.queues.keys()),
                "namespaces": set(self.namespace_collection.keys()),
            }

    def readset_delta(self, seal: dict):
        """The rows that moved since ``readset_seal``: the journal's
        typed marks past the seal cursor PLUS the belt-and-braces version
        sweep (rows whose _acct_gen/_status_version moved without a mark
        — exactly the unmarked-mutation class vclint VT009 exists for;
        the sweep makes the intersect safe against them instead of
        trusting the lint alone). Returns ``None`` when the journal
        window is unprovable — the caller must degrade to the
        whole-fingerprint discard."""
        with self._lock:
            marks = self.snap_keeper.marks_since(seal["cursor"])
            if marks is None:
                return None
            node_gens = seal["node_gens"]
            changed_nodes = {
                name for name, node in self.nodes.items()
                if node._acct_gen != node_gens.get(name)}
            changed_nodes.update(n for n in node_gens
                                 if n not in self.nodes)
            job_vers = seal["job_vers"]
            changed_jobs = {
                uid for uid, job in self.jobs.items()
                if job._status_version != job_vers.get(uid)}
            changed_jobs.update(u for u in job_vers
                                if u not in self.jobs)
            return {
                "marks": list(marks),
                "changed_nodes": changed_nodes,
                "changed_jobs": changed_jobs,
            }
