"""Columnar pod mirror — the cache's dense half.

The TPU encoder needs the scheduler-relevant pod fields (requests, priority,
creation time, predicate signature, trait flags) as dense arrays every
session. Extracting them from 50k Python objects costs ~100+ ms per cycle;
this table maintains them *incrementally* as the cache's event handlers
add/update/delete tasks, so encoding becomes a handful of numpy gathers.
It is the same architectural move the k8s scheduler's equivalence classes
and the reference's per-template predicate sharing gesture at
(predicates.go:281-299), taken to its TPU-native conclusion: the cluster
mirror IS the device-feed.

Concurrency: rows are assigned/freed under the table's own lock by the
cache handlers; every (re)assignment bumps the row's generation. A reader
(the encoder, which runs outside the cache lock) gathers under the table
lock and validates that each TaskInfo's recorded (row, generation) still
matches — a freed/reused row fails the check and the caller falls back to
the object walk, so stale data can never be encoded.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from volcano_tpu.api import objects
from volcano_tpu.api.pod_traits import pod_encode_traits

FLAG_PORTS = np.uint8(1)
FLAG_AFFINITY = np.uint8(2)
FLAG_REQ_EMPTY = np.uint8(4)
# references a PersistentVolumeClaim: volume assume/bind (StoreVolumeBinder)
# is live per-host logic the bulk solve does not model -> serial residue
FLAG_PVC = np.uint8(8)


class PodTable:
    _GROW = 1024

    def __init__(self):
        self.lock = threading.Lock()
        cap = self._GROW
        self._cap = cap
        self.cpu = np.zeros(cap, np.float64)
        self.mem = np.zeros(cap, np.float64)
        self.init_cpu = np.zeros(cap, np.float64)
        self.init_mem = np.zeros(cap, np.float64)
        self.priority = np.zeros(cap, np.int64)
        self.ctime = np.zeros(cap, np.float64)
        self.flags = np.zeros(cap, np.uint8)
        self.sig_id = np.zeros(cap, np.int32)
        self.gen = np.zeros(cap, np.int64)
        # uid per row as a ready-made object column: the encoder's task
        # ordering tie-breaks on uid, and building a 50k-string numpy array
        # from Python objects every session costs more than the lexsort
        # itself — here it is maintained incrementally like every column
        self.uid = np.empty(cap, object)
        self.scalar_cols: Dict[str, np.ndarray] = {}       # resreq scalars
        self.init_scalar_cols: Dict[str, np.ndarray] = {}  # init_resreq
        self._scalar_refs: Dict[str, int] = {}  # live rows using the scalar
        self.sig_keys: List[str] = []           # sig id -> key
        self._sig_ids: Dict[str, int] = {}
        self._uid_row: Dict[str, int] = {}
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._gen_counter = 0

    # -- maintenance (cache handlers) --------------------------------------

    def _grow(self) -> None:
        old = self._cap
        new = old + max(old, self._GROW)
        for name in ("cpu", "mem", "init_cpu", "init_mem", "priority",
                     "ctime", "flags", "sig_id", "gen"):
            arr = getattr(self, name)
            grown = np.zeros(new, arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        uid_grown = np.empty(new, object)
        uid_grown[:old] = self.uid
        self.uid = uid_grown
        for cols in (self.scalar_cols, self.init_scalar_cols):
            for rn, col in cols.items():
                grown = np.zeros(new, col.dtype)
                grown[:old] = col
                cols[rn] = grown
        self._free.extend(range(new - 1, old - 1, -1))
        self._cap = new

    def add(self, pod: objects.Pod, task) -> None:
        """Assign (or reassign) a row for `task` (which wraps `pod`) and
        record it on the TaskInfo as (row, row_gen)."""
        with self.lock:
            old = self._uid_row.pop(task.uid, None)
            if old is not None:
                self._release_row(old)
            if not self._free:
                self._grow()
            row = self._free.pop()
            self._gen_counter += 1
            self.gen[row] = self._gen_counter

            req = task.resreq
            init = task.init_resreq
            self.cpu[row] = req.milli_cpu
            self.mem[row] = req.memory
            self.init_cpu[row] = init.milli_cpu
            self.init_mem[row] = init.memory
            self.priority[row] = task.priority
            self.ctime[row] = pod.metadata.creation_timestamp
            key, ports, aff = pod_encode_traits(pod)
            flags = np.uint8(0)
            if ports:
                flags |= FLAG_PORTS
            if aff:
                flags |= FLAG_AFFINITY
            if req.is_empty():
                flags |= FLAG_REQ_EMPTY
            if any(v.persistent_volume_claim for v in pod.spec.volumes):
                flags |= FLAG_PVC
            self.flags[row] = flags
            sid = self._sig_ids.get(key)
            if sid is None:
                sid = self._sig_ids[key] = len(self.sig_keys)
                self.sig_keys.append(key)
            self.sig_id[row] = sid

            for rn, v in (req.scalar_resources or {}).items():
                self._set_scalar(self.scalar_cols, row, rn, v)
            for rn, v in (init.scalar_resources or {}).items():
                self._set_scalar(self.init_scalar_cols, row, rn, v)

            self._uid_row[task.uid] = row
            self.uid[row] = task.uid
            task.row = row
            task.row_gen = self._gen_counter

    def _set_scalar(self, cols: Dict[str, np.ndarray], row: int, rn: str,
                    value: float) -> None:
        col = cols.get(rn)
        if col is None:
            col = cols[rn] = np.zeros(self._cap, np.float64)
        if value:
            self._scalar_refs[rn] = self._scalar_refs.get(rn, 0) + 1
        col[row] = value

    def remove(self, uid: str) -> None:
        with self.lock:
            row = self._uid_row.pop(uid, None)
            if row is not None:
                self._release_row(row)

    def _release_row(self, row: int) -> None:
        self._gen_counter += 1
        self.gen[row] = self._gen_counter  # readers holding old gen fail
        self.uid[row] = None  # don't pin the uid string until row reuse
        for cols in (self.scalar_cols, self.init_scalar_cols):
            for rn, col in cols.items():
                if col[row]:
                    self._scalar_refs[rn] -= 1
                    col[row] = 0.0
        self._free.append(row)

    # -- reading (encoder) -------------------------------------------------

    def scalar_names(self) -> List[str]:
        """Scalars referenced by any live row (may over-include rows whose
        scalar value was 0 — harmless: an extra all-zero resource dim)."""
        with self.lock:
            return [rn for rn, c in self._scalar_refs.items() if c > 0]

    def gather(self, rows: np.ndarray, gens: np.ndarray,
               scalar_names: List[str]) -> Optional[dict]:
        """Validated snapshot of the given rows, or None when ANY row's
        generation no longer matches (caller falls back to the object
        walk). Runs under the table lock so rows cannot be reused
        mid-gather."""
        with self.lock:
            if rows.size and (rows.min() < 0 or rows.max() >= self._cap):
                return None
            if not np.array_equal(self.gen[rows], gens):
                return None
            out = {
                "uid": self.uid[rows],
                "cpu": self.cpu[rows],
                "mem": self.mem[rows],
                "init_cpu": self.init_cpu[rows],
                "init_mem": self.init_mem[rows],
                "priority": self.priority[rows],
                "ctime": self.ctime[rows],
                "flags": self.flags[rows],
                "sig_id": self.sig_id[rows],
                "scalars": {},
                "init_scalars": {},
            }
            zeros = None
            for rn in scalar_names:
                for key, cols in (("scalars", self.scalar_cols),
                                  ("init_scalars", self.init_scalar_cols)):
                    col = cols.get(rn)
                    if col is None:
                        if zeros is None:
                            zeros = np.zeros(rows.size, np.float64)
                        out[key][rn] = zeros
                    else:
                        out[key][rn] = col[rows]
            return out
