"""Snapshot-captured columnar node axis — the node-side twin of the pod
table (podtable.py).

The encoder's node arrays (idle/used/allocatable matrices, static predicate
bits, taint/resident/releasing flags, task counts) cost a handful of
O(nodes) Python walks per session when gathered from NodeInfo objects.
cache.snapshot() already clones every ready node; capturing the columns in
the same pass moves that cost off the measured session-actions path and
turns encode's node section into array slices.

Consistency: every NodeInfo resource mutation bumps node._acct_gen
(node_info.py); the capture records the clone's generation, and the encoder
re-validates all generations before trusting the columns (encoder.py
_node_axis_from_capture). A mismatch — any node touched between snapshot
and encode, e.g. by an action ordered before allocate — falls back to the
object walk, so stale columns can never be encoded.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

# flag bits (uint16)
F_READY = np.uint16(1)
F_NET_UNAVAILABLE = np.uint16(2)
F_MEM_PRESSURE = np.uint16(4)
F_DISK_PRESSURE = np.uint16(8)
F_PID_PRESSURE = np.uint16(16)
F_UNSCHEDULABLE = np.uint16(32)
F_RELEASING = np.uint16(64)
F_BLOCKING_TAINTS = np.uint16(128)
F_RESIDENT_PODS = np.uint16(256)


class NodeAxis:
    """Columns over the snapshot's ready nodes, name-sorted (the encoder's
    node order). ``scalars[attr]`` maps scalar resource name -> [N] array;
    attrs are "idle" / "used" / "alloc".

    The axis is LONG-LIVED when owned by the snapshot keeper
    (cache/snapkeeper.py): rows are patched in place between sessions for
    the nodes that actually changed, and ``epoch`` counts content changes
    so downstream caches (the encoder's node matrices, the solver's packed
    buffers) can trust an unchanged-epoch axis without re-reading it."""

    __slots__ = ("names", "nodes", "gens", "flags", "cpu", "mem",
                 "scalars", "scalar_names", "node_cnt", "max_tasks",
                 "epoch", "mat_cache")

    def __init__(self, names: List[str], nodes: list, gens: np.ndarray,
                 flags: np.ndarray, cpu: Dict[str, np.ndarray],
                 mem: Dict[str, np.ndarray],
                 scalars: Dict[str, Dict[str, np.ndarray]],
                 scalar_names: List[str],
                 node_cnt: np.ndarray, max_tasks: np.ndarray):
        self.names = names
        self.nodes = nodes
        self.gens = gens
        self.flags = flags
        self.cpu = cpu
        self.mem = mem
        self.scalars = scalars
        self.scalar_names = scalar_names
        self.node_cnt = node_cnt
        self.max_tasks = max_tasks
        self.epoch = 0
        # encoder-side memo of derived per-epoch products (node matrices);
        # invalidated wholesale when epoch moves (encoder._node_matrix)
        self.mat_cache: dict = {}

    def total_alloc(self):
        """Cluster-total allocatable as (milli_cpu, memory, {scalar: sum})
        — the columnar replacement for the per-node Resource.add loop the
        drf/proportion session-open passes used to run (drf.go:78-80).
        max_task_num deliberately excluded, as Resource.add excludes it."""
        return (
            float(self.cpu["alloc"].sum()),
            float(self.mem["alloc"].sum()),
            {rn: float(col.sum())
             for rn, col in self.scalars["alloc"].items()},
        )

    def add_total_into(self, res) -> None:
        """res += cluster-total allocatable (columnar). The one shared
        implementation of the axis-vs-walk totaling fold for session-open
        plugins (drf/proportion)."""
        mc, mem, scal = self.total_alloc()
        res.milli_cpu += mc
        res.memory += mem
        for rn, q in scal.items():
            res.add_scalar(rn, q)

    def validate(self) -> bool:
        """True when every captured node's accounting generation is
        unchanged (nothing mutated node state since snapshot)."""
        nodes = self.nodes
        n = len(nodes)
        if n == 0:
            return True
        gens = np.fromiter((nd._acct_gen for nd in nodes), np.int64, n)
        return bool(np.array_equal(gens, self.gens))


def add_total_allocatable(ssn, res) -> None:
    """res += total allocatable over the session's ready nodes, via the
    snapshot-captured axis when it is still generation-valid, else the
    per-node walk. Shared by drf/proportion on_session_open."""
    axis = getattr(ssn, "node_axis", None)
    if axis is not None and axis.validate():
        axis.add_total_into(res)
    else:
        for node in ssn.nodes.values():
            res.add(node.allocatable)


def _node_flag_bits(info) -> int:
    node = info.node
    bits = 0
    if node is not None:
        for cond in node.status.conditions:
            if cond.status != "True":
                continue
            if cond.type == "Ready":
                bits |= int(F_READY)
            elif cond.type == "NetworkUnavailable":
                bits |= int(F_NET_UNAVAILABLE)
            elif cond.type == "MemoryPressure":
                bits |= int(F_MEM_PRESSURE)
            elif cond.type == "DiskPressure":
                bits |= int(F_DISK_PRESSURE)
            elif cond.type == "PIDPressure":
                bits |= int(F_PID_PRESSURE)
        if node.spec.unschedulable:
            bits |= int(F_UNSCHEDULABLE)
        if any(t.effect in ("NoSchedule", "NoExecute")
               for t in node.spec.taints):
            bits |= int(F_BLOCKING_TAINTS)
    if not info.releasing.is_empty():
        bits |= int(F_RELEASING)
    if info.tasks:
        bits |= int(F_RESIDENT_PODS)
    return bits


def refresh_rows(axis: NodeAxis, updates) -> bool:
    """Patch the axis in place for ``updates`` = [(row_index, node), ...]
    (the snapshot keeper's dirty rows). Returns False when a node carries a
    scalar resource the axis has no column for — the caller must fall back
    to a full ``capture_node_axis`` (new resource dimensions reshape every
    scalar column). Bumps ``epoch`` and drops the derived-matrix memo."""
    scalar_set = set(axis.scalar_names)
    for _, nd in updates:
        for field in ("idle", "used", "allocatable"):
            sr = getattr(nd, field).scalar_resources
            if sr and not scalar_set.issuperset(sr):
                return False
    for i, nd in updates:
        axis.nodes[i] = nd
        axis.gens[i] = nd._acct_gen
        axis.flags[i] = _node_flag_bits(nd)
        axis.node_cnt[i] = len(nd.tasks)
        axis.max_tasks[i] = nd.allocatable.max_task_num
        for attr, field in (("idle", "idle"), ("used", "used"),
                            ("alloc", "allocatable")):
            r = getattr(nd, field)
            axis.cpu[attr][i] = r.milli_cpu
            axis.mem[attr][i] = r.memory
            cols = axis.scalars[attr]
            sr = r.scalar_resources
            for rn, col in cols.items():
                col[i] = sr.get(rn, 0.0) if sr else 0.0
    if updates:
        # the axis epoch is a DERIVED channel: rows only refresh after
        # the keeper's marks / _acct_gen sweep already moved the sealed
        # dirty-epoch and acct-sum components, so the fingerprint covers
        # it transitively (it memo-keys encoder matrices, nothing else)
        axis.epoch += 1  # vclint: disable=VT009 - derived memo key; sealed transitively via dirty_epoch + acct sum
        axis.mat_cache.clear()
    return True


def capture_node_axis(nodes_by_name: Dict[str, object]) -> Optional[NodeAxis]:
    """Build the columnar axis from the snapshot's (already cloned) ready
    nodes. Called by cache.snapshot() — the one place that already walks
    every node each cycle."""
    names = sorted(nodes_by_name)
    nodes = [nodes_by_name[n] for n in names]
    n = len(nodes)
    gens = np.fromiter((nd._acct_gen for nd in nodes), np.int64, n) \
        if n else np.zeros(0, np.int64)
    flags = np.fromiter((_node_flag_bits(nd) for nd in nodes), np.uint16, n) \
        if n else np.zeros(0, np.uint16)

    cpu: Dict[str, np.ndarray] = {}
    mem: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Dict[str, np.ndarray]] = {}
    scalar_name_set: set = set()
    attr_objs = {}
    for attr, field in (("idle", "idle"), ("used", "used"),
                        ("alloc", "allocatable")):
        ress = [getattr(nd, field) for nd in nodes]
        attr_objs[attr] = ress
        cpu[attr] = np.array([r.milli_cpu for r in ress], np.float64)
        mem[attr] = np.array([r.memory for r in ress], np.float64)
        for r in ress:
            if r.scalar_resources:
                scalar_name_set.update(r.scalar_resources)
    for attr in ("idle", "used", "alloc"):
        cols = scalars[attr] = {}
        if scalar_name_set:
            ress = attr_objs[attr]
            for rn in sorted(scalar_name_set):
                cols[rn] = np.array(
                    [(r.scalar_resources or {}).get(rn, 0.0) for r in ress],
                    np.float64)

    node_cnt = np.fromiter((len(nd.tasks) for nd in nodes), np.int32, n) \
        if n else np.zeros(0, np.int32)
    max_tasks = np.fromiter(
        (nd.allocatable.max_task_num for nd in nodes), np.int32, n) \
        if n else np.zeros(0, np.int32)
    return NodeAxis(names, nodes, gens, flags, cpu, mem, scalars,
                    sorted(scalar_name_set), node_cnt, max_tasks)
