"""Scheduler cache: the cluster mirror + effector seam."""

from volcano_tpu.scheduler.cache.interface import (
    Binder,
    Evictor,
    StatusUpdater,
    VolumeBinder,
)
from volcano_tpu.scheduler.cache.cache import (
    SchedulerCache,
    DefaultBinder,
    DefaultEvictor,
    DefaultStatusUpdater,
    DefaultVolumeBinder,
)
