"""Leader election over a store resource lock.

The reference runs the scheduler and the controller manager as active/passive
HA pairs coordinated through a ConfigMap resource lock
(/root/reference/cmd/scheduler/app/server.go:131-160,
cmd/controllers/app/server.go:110-140, using client-go leaderelection with
lease 15s / renew 10s / retry 5s, server.go:52-54). This module is the
in-process analog: the lock record lives in a ConfigMap in the store, writes
go through the store's compare-and-swap (`Store.update(expect_version=...)`),
and candidates race exactly as the client-go implementation does — read the
record, and either (a) find it expired and try to take it, or (b) find
themselves the holder and renew. Exactly one candidate holds the lease at any
moment; the holder runs its workload callback, and a holder that fails to
renew inside the renew deadline stops leading so the standby can take over.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from volcano_tpu.api import objects
from volcano_tpu.store.store import ConflictError, Store

logger = logging.getLogger(__name__)

# client-go defaults used by the reference (cmd/scheduler/app/server.go:52-54)
DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 5.0

_RECORD_KEY = "control-plane.alpha.volcano/leader"


@dataclass
class LeaderElectionRecord:
    holder_identity: str
    lease_duration: float
    acquire_time: float
    renew_time: float
    leader_transitions: int = 0

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @classmethod
    def from_json(cls, text: str) -> "LeaderElectionRecord":
        return cls(**json.loads(text))


class ResourceLock:
    """ConfigMap-annotation resource lock (client-go resourcelock semantics:
    the record is serialized into an annotation; create/update are guarded by
    the store's optimistic concurrency)."""

    def __init__(self, store: Store, namespace: str, name: str, identity: str):
        self.store = store
        self.namespace = namespace
        self.name = name
        self.identity = identity

    def get(self) -> Optional[tuple]:
        """(record_or_None, resource_version) when the lock ConfigMap
        exists — record None means the annotation is missing/unparseable
        and the caller must take the CAS-update path, NOT create (create
        would conflict forever and deadlock the election). Returns None
        only when the ConfigMap itself doesn't exist."""
        cm = self.store.try_get("ConfigMap", self.namespace, self.name)
        if cm is None:
            return None
        raw = (cm.metadata.annotations or {}).get(_RECORD_KEY)
        if not raw:
            return (None, cm.metadata.resource_version)
        try:
            return (LeaderElectionRecord.from_json(raw),
                    cm.metadata.resource_version)
        except (ValueError, TypeError):
            return (None, cm.metadata.resource_version)

    def create(self, record: LeaderElectionRecord) -> bool:
        cm = objects.ConfigMap(
            metadata=objects.ObjectMeta(
                name=self.name, namespace=self.namespace,
                annotations={_RECORD_KEY: record.to_json()}))
        try:
            self.store.create(cm)
            return True
        except ConflictError:
            return False

    def update(self, record: LeaderElectionRecord, expect_version: int) -> bool:
        cm = self.store.try_get("ConfigMap", self.namespace, self.name)
        if cm is None:
            return False
        annotations = dict(cm.metadata.annotations or {})
        annotations[_RECORD_KEY] = record.to_json()
        new = objects.ConfigMap(
            metadata=objects.ObjectMeta(
                name=self.name, namespace=self.namespace,
                annotations=annotations))
        new.metadata.uid = cm.metadata.uid
        new.metadata.creation_timestamp = cm.metadata.creation_timestamp
        try:
            self.store.update(new, expect_version=expect_version)
            return True
        except (ConflictError, KeyError):
            return False


class LeaderElector:
    """Run-loop elector: acquire -> on_started_leading, renew until lost ->
    on_stopped_leading. `run()` blocks until `stop()`; callbacks fire on the
    elector thread. `is_leader()` is safe from any thread."""

    def __init__(
        self,
        lock: ResourceLock,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Callable[[], None],
        on_new_leader: Optional[Callable[[str], None]] = None,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        renew_deadline: float = DEFAULT_RENEW_DEADLINE,
        retry_period: float = DEFAULT_RETRY_PERIOD,
        clock: Callable[[], float] = time.monotonic,
    ):
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        if retry_period >= renew_deadline:
            raise ValueError("retry_period must be < renew_deadline")
        self.lock = lock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.on_new_leader = on_new_leader
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._clock = clock
        self._stop = threading.Event()
        self._leading = False
        self._observed_holder = ""
        self._observed_record_key = None
        self._observed_time = 0.0
        self._thread: Optional[threading.Thread] = None
        # the lease epoch of the most recent acquisition: the record's
        # leader_transitions + 1, the fencing token every mutating write
        # of this leadership term carries (store/store.py FencedError).
        # DELIBERATELY never reset on lost leadership — a deposed
        # workload's in-flight writes must keep their stale stamp so the
        # store rejects them, rather than fall back to unfenced.
        self._epoch = 0

    # -- public ------------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leading

    def epoch(self) -> int:
        """The fencing epoch of the most recent acquisition (0 = never
        led). Valid for writes only while ``is_leader()``; a deposed term
        keeps its stale epoch by design."""
        return self._epoch

    def start(self) -> None:
        """Run the elector loop on a daemon thread."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.renew_deadline + 1.0)

    def run(self) -> None:
        last_renew = self._clock()
        try:
            while not self._stop.is_set():
                try:
                    acquired = self._try_acquire_or_renew()
                except Exception:
                    # store hiccups must not kill the elector thread — a
                    # dead elector is a silently dead leader (healthz keeps
                    # answering while nothing schedules). While leading and
                    # still inside the renew deadline, a transient error is
                    # tolerated exactly like client-go tolerates failed
                    # renews: keep leading, retry next period. Only past
                    # the deadline does it count as a lost lease.
                    logger.exception("leader election cycle failed for %s",
                                     self.lock.identity)
                    if (self._leading
                            and self._clock() - last_renew < self.renew_deadline):
                        self._stop.wait(self.retry_period)
                        continue
                    acquired = False
                if acquired:
                    last_renew = self._clock()
                    if not self._leading:
                        logger.info("%s became leader (epoch %d)",
                                    self.lock.identity, self._epoch)
                        try:
                            from volcano_tpu.scheduler import metrics

                            metrics.register_leader_transition()
                        except Exception:  # pragma: no cover
                            pass
                        # callback BEFORE publishing is_leader(): an observer
                        # that polls is_leader() must find the workload
                        # already started
                        try:
                            self.on_started_leading()
                        except Exception:
                            # workload failed to start: tear down whatever
                            # partially started, release the lease, and step
                            # down explicitly (never a silently dead leader
                            # holding the lock), then retry — the standby or
                            # this candidate re-acquires and restarts the
                            # workload. Teardown/release are themselves
                            # guarded: the elector thread survives store
                            # errors raised while cleaning up.
                            logger.exception(
                                "workload start failed for %s; stepping down",
                                self.lock.identity)
                            try:
                                self.on_stopped_leading()
                            except Exception:
                                logger.exception(
                                    "workload teardown failed for %s",
                                    self.lock.identity)
                            try:
                                self._release()
                            except Exception:
                                logger.exception(
                                    "lease release failed for %s",
                                    self.lock.identity)
                            self._stop.wait(self.retry_period)
                            continue
                        self._leading = True
                    self._stop.wait(self.retry_period)
                else:
                    if self._leading:
                        self._leading = False
                        logger.info("%s lost leadership", self.lock.identity)
                        try:
                            self.on_stopped_leading()
                        except Exception:
                            # teardown raising (e.g. during the same store
                            # outage that cost the lease) must not kill the
                            # elector: this node keeps contending
                            logger.exception(
                                "workload teardown failed for %s",
                                self.lock.identity)
                    self._stop.wait(self.retry_period)
        finally:
            if self._leading:
                self._leading = False
                self._release()
                self.on_stopped_leading()

    def healthy(self) -> bool:
        """Elector liveness for healthz: the loop thread (when started) is
        still running. A crashed elector must flip readiness, not keep
        serving 200 with no scheduler behind it."""
        return self._thread is None or self._thread.is_alive()

    # -- internals ---------------------------------------------------------

    def _observe(self, holder: str) -> None:
        if holder != self._observed_holder:
            self._observed_holder = holder
            if self.on_new_leader is not None:
                self.on_new_leader(holder)

    def _observe_record(self, record) -> None:
        """Track WHEN this elector locally observed the record last change
        (client-go leaderelection.go observedTime): lease expiry is judged
        as 'unchanged for a full lease_duration on MY clock', never by
        comparing the record's timestamps against the local clock — the
        holder's clock (time.monotonic has a per-host epoch) and ours need
        not be related when the lock lives in a remote store."""
        key = (record.holder_identity, record.acquire_time,
               record.renew_time)
        if key != self._observed_record_key:
            self._observed_record_key = key
            self._observed_time = self._clock()
        self._observe(record.holder_identity)

    def _try_acquire_or_renew(self) -> bool:
        now = self._clock()
        identity = self.lock.identity
        got = self.lock.get()

        if got is None:
            record = LeaderElectionRecord(
                holder_identity=identity,
                lease_duration=self.lease_duration,
                acquire_time=now, renew_time=now)
            if self.lock.create(record):
                self._epoch = record.leader_transitions + 1
                self._observe_record(record)
                return True
            return False  # raced; retry next period

        record, version = got
        if record is None:
            # lock object exists but carries no readable record (corrupt or
            # version-skewed annotation): claim it through the CAS update
            new = LeaderElectionRecord(
                holder_identity=identity,
                lease_duration=self.lease_duration,
                acquire_time=now, renew_time=now)
            if self.lock.update(new, version):
                self._epoch = new.leader_transitions + 1
                self._observe_record(new)
                return True
            return False

        self._observe_record(record)
        if record.holder_identity != identity:
            # expiry by LOCAL observation age, not by the record's
            # timestamps: cross-host monotonic clocks share no epoch
            # (client-go leaderelection.go:281-290 does the same). An
            # EMPTY holder is a clean release — no lease to wait out
            if record.holder_identity and \
                    now < self._observed_time + self.lease_duration:
                return False  # current leader still within its lease
            # lease expired: try to take over (CAS rejects racing standbys)
            new = LeaderElectionRecord(
                holder_identity=identity,
                lease_duration=self.lease_duration,
                acquire_time=now, renew_time=now,
                leader_transitions=record.leader_transitions + 1)
            if self.lock.update(new, version):
                self._epoch = new.leader_transitions + 1
                return True
            return False

        # we are the holder: renew
        record.renew_time = now
        record.lease_duration = self.lease_duration
        if self.lock.update(record, version):
            self._epoch = record.leader_transitions + 1
            return True
        # CAS failure while holding means someone stole an expired lease
        return False

    def _release(self) -> None:
        """Drop the lease on clean shutdown so the standby takes over in one
        retry period instead of a full lease duration."""
        got = self.lock.get()
        if got is None:
            return
        record, version = got
        if record is None or record.holder_identity != self.lock.identity:
            return
        # client-go's release: EMPTY the holder (observation-based expiry
        # deliberately ignores timestamps, so a zeroed renew_time alone
        # would read as just another record change and make the standby
        # wait a full lease; an empty holder bypasses the lease wait)
        record.holder_identity = ""
        record.renew_time = 0.0
        self.lock.update(record, version)
