"""backfill — place BestEffort (zero-request) tasks on the first
predicate-passing node, without scoring or statements
(volcano pkg/scheduler/actions/backfill/backfill.go:41-91)."""

from __future__ import annotations

import logging

from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import FitErrors, FitFailure
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util import scheduler_helper as helper

logger = logging.getLogger(__name__)


class BackfillAction(Action):
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        from volcano_tpu.ops import evict as evict_mod
        from volcano_tpu.ops import preemptview

        # batched backfill (ops/evict.py): one device dispatch decides
        # every zero-request placement (first feasible node in name order
        # under the evolving pod-count); the host replays via ssn.allocate
        # with the same FitErrors/replay-budget machinery as below.
        # VOLCANO_TPU_EVICT=0 forces this oracle path.
        plan = evict_mod.build(ssn, "backfill")
        if plan is not None and plan.run():
            return

        # dense per-signature feasibility rows (same candidates, same name
        # order as the serial walk) when tpuscore is on; the predicate
        # closure sweep remains the fallback and oracle
        view = preemptview.build(ssn)

        all_nodes = helper.get_node_list(ssn.nodes)
        # budget for full per-node diagnostics replay on view-path failures:
        # each replay costs O(nodes) predicate calls, so only the first few
        # failed tasks per session get serial-fidelity reasons — a taint
        # rollout failing thousands of best-effort pods must not turn the
        # fast dense-view path back into the O(tasks x nodes) sweep
        replay_budget = 8
        for job in list(ssn.jobs.values()):
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue

            for task in list(job.task_status_index.get(TaskStatus.PENDING, {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                allocated = False
                fe = FitErrors()
                candidates = view.masked_nodes_in_name_order(task) \
                    if view is not None else None
                fell_back = candidates is None
                if fell_back:
                    def _feasible(_task=task, _fe=fe):
                        for nd in all_nodes:
                            try:
                                ssn.predicate_fn(_task, nd)
                            except FitFailure as err:
                                _fe.set_node_error(
                                    nd.name, err.fit_error(_task, nd))
                                continue
                            yield nd
                    candidates = _feasible()
                tried = 0
                for node in candidates:
                    tried += 1
                    try:
                        ssn.allocate(task, node.name)
                    except (KeyError, RuntimeError) as err:
                        logger.error("Failed to bind Task %s on %s: %s", task.uid, node.name, err)
                        continue
                    if view is not None:
                        view.on_pipeline(node.name, task)
                        if fell_back and view.needs_poison(task):
                            # an affinity-carrying pod became resident:
                            # later masks/scores would be stale
                            view.poison()
                    allocated = True
                    break
                if not allocated:
                    if view is not None and not fe.nodes:
                        if tried == 0 and replay_budget > 0:
                            # dense-view failure path: replay the serial
                            # predicate chain to recover the per-node
                            # reasons the serial walk records (bounded by
                            # replay_budget — see above)
                            replay_budget -= 1
                            for nd in all_nodes:
                                try:
                                    ssn.predicate_fn(task, nd)
                                except FitFailure as err:
                                    fe.set_node_error(
                                        nd.name, err.fit_error(task, nd))
                        if not fe.nodes:
                            fe.set_error(
                                "0/%d nodes are feasible for backfill"
                                % len(all_nodes) if tried == 0 else
                                "%d feasible nodes rejected the backfill "
                                "allocation" % tried)
                    job.nodes_fit_errors[task.uid] = fe
