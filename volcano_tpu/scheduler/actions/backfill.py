"""backfill — place BestEffort (zero-request) tasks on the first
predicate-passing node, without scoring or statements
(volcano pkg/scheduler/actions/backfill/backfill.go:41-91)."""

from __future__ import annotations

import logging

from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import FitErrors, FitFailure
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util import scheduler_helper as helper

logger = logging.getLogger(__name__)


class BackfillAction(Action):
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        all_nodes = helper.get_node_list(ssn.nodes)
        for job in list(ssn.jobs.values()):
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue

            for task in list(job.task_status_index.get(TaskStatus.PENDING, {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                allocated = False
                fe = FitErrors()
                for node in all_nodes:
                    try:
                        ssn.predicate_fn(task, node)
                    except FitFailure as err:
                        fe.set_node_error(node.name, err.fit_error(task, node))
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except (KeyError, RuntimeError) as err:
                        logger.error("Failed to bind Task %s on %s: %s", task.uid, node.name, err)
                        continue
                    allocated = True
                    break
                if not allocated:
                    job.nodes_fit_errors[task.uid] = fe
