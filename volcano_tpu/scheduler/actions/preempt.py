"""preempt — within-queue job-vs-job, then intra-job task-vs-task preemption
(volcano pkg/scheduler/actions/preempt/preempt.go:45-277).

Victims come from the tiered ``ssn.preemptable`` intersection; lowest-priority
victims are evicted until the preemptor fits; the preemptor is Pipelined onto
the node. The per-job Statement commits when JobPipelined holds.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from volcano_tpu.api import objects
from volcano_tpu.api.resource import (
    MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR, Resource)
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util import scheduler_helper as helper
from volcano_tpu.scheduler.util.priority_queue import (
    PriorityQueue,
    make_task_queue,
)

logger = logging.getLogger(__name__)


class PreemptAction(Action):
    def name(self) -> str:
        return "preempt"

    def execute(self, ssn) -> None:
        from volcano_tpu.ops import evict as evict_mod
        from volcano_tpu.ops import preemptview, victimview

        # batched device eviction (ops/evict.py): the whole action — job
        # heaps, candidate windows, victim tiers, eviction cuts, gang
        # commit/discard — runs as ONE packed device dispatch and the host
        # replays the committed ops through the real Statements. Bindings
        # and evictions are identical to the walk below within the modeled
        # envelope (VOLCANO_TPU_EVICT=0 forces this oracle path; see
        # tests/test_evict_kernel.py).
        plan = evict_mod.build(ssn, "preempt")
        if plan is not None and plan.run():
            return

        # dense (preemptor x node) feasibility/score rows replace the
        # serial per-task O(nodes) closure sweeps when tpuscore is on;
        # victim selection and Statement authority stay here (SURVEY §7)
        view = preemptview.build(ssn)
        # batched tiered-intersection victim proposal (ops/victimview.py);
        # None => every node uses the serial ssn.preemptable dispatch
        selector = victimview.build(ssn, "preemptable") \
            if view is not None else None

        # per-session metric accumulator: the per-candidate Counter.inc
        # (lock + dict op, ~6us) x thousands of candidates is measurable on
        # the preempt hot path; scrape-time values are identical when the
        # totals land once at the end of the action
        stats = {"victims": 0, "attempts": 0}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, object] = {}
        under_request: List = []
        queues: Dict[str, object] = {}

        for job in ssn.jobs.values():
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues.setdefault(queue.uid, queue)

            if job.task_status_index.get(TaskStatus.PENDING):
                if job.queue not in preemptors_map:
                    preemptors_map[job.queue] = PriorityQueue(cmp_fn=ssn.job_order_cmp)
                preemptors_map[job.queue].push(job)
                under_request.append(job)
                preemptor_tasks[job.uid] = make_task_queue(
                    ssn, job.task_status_index[TaskStatus.PENDING].values())

        for queue in queues.values():
            # Preemption between jobs within the queue.
            while True:
                preemptors = preemptors_map.get(queue.uid)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()

                stmt = ssn.statement()
                assigned = False
                stmt_pipelines: List = []  # (node_name, task) to unwind
                poison0 = view.poison_state() if view is not None else False
                while True:
                    if preemptor_tasks[preemptor_job.uid].empty():
                        break
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def job_filter(task, _preemptor=preemptor, _job=preemptor_job):
                        if task.status != TaskStatus.RUNNING:
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return job.queue == _job.queue and _preemptor.job != task.job

                    host = _preempt(ssn, stmt, preemptor, ssn.nodes,
                                    job_filter, view, selector, stats)
                    if host is not None:
                        assigned = True
                        if view is not None:
                            view.on_pipeline(host, preemptor)
                            stmt_pipelines.append((host, preemptor))

                    if ssn.job_pipelined(preemptor_job):
                        stmt.commit()
                        break

                if not ssn.job_pipelined(preemptor_job):
                    # discard restores the cluster exactly — including any
                    # poison raised by THIS statement's fallback pipelines
                    # (the un-modeled pod is resident no longer)
                    stmt.discard()
                    if view is not None:
                        for host, task in stmt_pipelines:
                            view.on_unpipeline(host, task)
                        view.restore_poison(poison0)
                    continue

                if assigned:
                    preemptors.push(preemptor_job)

            # Preemption between tasks within one job.
            for job in under_request:
                while True:
                    tasks = preemptor_tasks.get(job.uid)
                    if tasks is None or tasks.empty():
                        break
                    preemptor = tasks.pop()

                    def task_filter(task, _preemptor=preemptor):
                        if task.status != TaskStatus.RUNNING:
                            return False
                        return _preemptor.job == task.job

                    stmt = ssn.statement()
                    host = _preempt(ssn, stmt, preemptor, ssn.nodes,
                                    task_filter, view, selector, stats)
                    if host is not None and view is not None:
                        view.on_pipeline(host, preemptor)
                    stmt.commit()
                    if host is None:
                        break

        if stats["victims"]:
            metrics.update_preemption_victims(stats["victims"])
        if stats["attempts"]:
            metrics.register_preemption_attempts(stats["attempts"])


def _preempt(ssn, stmt, preemptor, nodes, task_filter, view=None,
             selector=None, stats=None):
    """(preempt.go:180-260). Returns the pipelined node name, or None.

    With a dense view the candidate stream (feasibility window + score
    order) comes from vectorized rows, and a victim selector batches the
    tiered plugin intersection; the eviction cut below is identical
    either way."""
    candidates = view.candidates(preemptor) if view is not None else None
    fell_back = candidates is None
    if fell_back:  # no view, or un-modeled preemptor (ports/affinity)
        all_nodes = helper.get_node_list(nodes)
        found_nodes, _ = helper.predicate_nodes(preemptor, all_nodes, ssn.predicate_fn)
        node_scores = helper.prioritize_nodes(
            preemptor, found_nodes,
            ssn.batch_node_order_fn, ssn.node_order_map_fn, ssn.node_order_reduce_fn)
        candidates = helper.sort_nodes(node_scores)

    # scalar-free requests (the overwhelmingly common case) take a pure
    # float cut below: the accumulate/epsilon-compare sequence is
    # arithmetic-identical to Resource.add + less_equal, minus the object
    # churn per victim — any scalar on either side restores the oracle
    init_req = preemptor.init_resreq
    init_scalars = init_req.scalar_resources
    fast_req = init_scalars is None or not any(
        v > MIN_MILLI_SCALAR for v in init_scalars.values())

    for node in candidates:
        # shared_clone: victims need independent status words for the
        # evict bookkeeping but never mutate their request Resources
        preemptees = [
            task.shared_clone()
            for task in node.tasks.values()
            if task_filter is None or task_filter(task)
        ]
        victims = (selector.victims(preemptor, preemptees)
                   if selector is not None
                   else ssn.preemptable(preemptor, preemptees))
        if stats is not None:
            stats["victims"] += len(victims)
        else:
            metrics.update_preemption_victims(len(victims))

        if not _validate_victims(victims, preemptor.init_resreq):
            continue

        fast = fast_req and not any(v.resreq.scalar_resources
                                    for v in victims)
        preempted = Resource.empty()
        resreq = None if fast else preemptor.init_resreq.clone()
        need_cpu, need_mem = init_req.milli_cpu, init_req.memory
        got_cpu = got_mem = 0.0

        # lowest-priority victims first (inverse task order)
        victims_queue = make_task_queue(ssn, victims, reverse=True)
        while not victims_queue.empty():
            preemptee = victims_queue.pop()
            try:
                stmt.evict(preemptee, "preempt")
            except Exception as e:
                logger.error("Failed to preempt Task <%s/%s> for <%s/%s>: %s",
                             preemptee.namespace, preemptee.name,
                             preemptor.namespace, preemptor.name, e)
                continue
            if fast:
                vr = preemptee.resreq
                got_cpu += vr.milli_cpu
                got_mem += vr.memory
                if (need_cpu < got_cpu or abs(need_cpu - got_cpu)
                        < MIN_MILLI_CPU) and \
                   (need_mem < got_mem or abs(need_mem - got_mem)
                        < MIN_MEMORY):
                    break
            else:
                preempted.add(preemptee.resreq)
                if resreq.less_equal(preempted):
                    break

        if stats is not None:
            stats["attempts"] += 1
        else:
            metrics.register_preemption_attempts()

        if fast:
            covered = (need_cpu < got_cpu or abs(need_cpu - got_cpu)
                       < MIN_MILLI_CPU) and \
                      (need_mem < got_mem or abs(need_mem - got_mem)
                       < MIN_MEMORY)
        else:
            covered = preemptor.init_resreq.less_equal(preempted)
        if covered:
            stmt.pipeline(preemptor, node.name)
            if fell_back and view is not None and view.needs_poison(preemptor):
                # pipeline fires allocate events IMMEDIATELY (statement.py),
                # so this pod's (anti-)affinity is resident right now and
                # cached masks are stale for the very next candidate; the
                # action restores the pre-statement poison state on discard
                view.poison()
            return node.name

    return None


def _validate_victims(victims, resreq) -> bool:
    """(preempt.go:262-277)"""
    if not victims:
        return False
    all_res = Resource.empty()
    for v in victims:
        all_res.add(v.resreq)
    return not all_res.less(resreq)
