"""reclaim — cross-queue reclamation for starved queues
(volcano pkg/scheduler/actions/reclaim/reclaim.go:42-205).

A non-overused queue's pending job evicts Running tasks from *other* queues
(via the tiered ``ssn.reclaimable`` intersection — the proportion plugin
enforces the deserved-share floor) and pipelines the reclaimer. Direct
``ssn.evict``/``ssn.pipeline``, no statement.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from volcano_tpu.api import objects
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import FitFailure
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util import scheduler_helper as helper
from volcano_tpu.scheduler.util.priority_queue import (
    PriorityQueue,
    make_task_queue,
)

logger = logging.getLogger(__name__)


class ReclaimAction(Action):
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn) -> None:
        from volcano_tpu.ops import evict as evict_mod
        from volcano_tpu.ops import preemptview, victimview

        # batched device eviction (ops/evict.py): queue rotation, tiered
        # victim masks, deserved-floor walks and the eviction cuts run as
        # one packed device dispatch; the host replays the op log through
        # ssn.evict/ssn.pipeline in serial order. VOLCANO_TPU_EVICT=0
        # forces the oracle walk below (tests/test_evict_kernel.py).
        plan = evict_mod.build(ssn, "reclaim")
        if plan is not None and plan.run():
            return

        # dense per-signature feasibility rows replace the per-task O(nodes)
        # predicate closure sweep when tpuscore is on (same candidates, name
        # order, as reclaim.go's full node walk); the victim selector
        # batches the tiered Reclaimable intersection on dense nodes
        view = preemptview.build(ssn)
        selector = victimview.build(ssn, "reclaimable") \
            if view is not None else None

        queues = PriorityQueue(cmp_fn=ssn.queue_order_cmp)
        queue_set = set()
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, object] = {}

        for job in ssn.jobs.values():
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            if job.task_status_index.get(TaskStatus.PENDING):
                if job.queue not in preemptors_map:
                    preemptors_map[job.queue] = PriorityQueue(cmp_fn=ssn.job_order_cmp)
                preemptors_map[job.queue].push(job)
                preemptor_tasks[job.uid] = make_task_queue(
                    ssn, job.task_status_index[TaskStatus.PENDING].values())

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue

            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            candidates = view.masked_nodes_in_name_order(task) \
                if view is not None else None
            fell_back = candidates is None
            if fell_back:
                def _serial_feasible(_task=task):
                    # lazy, like the original walk: predicates run only up
                    # to the node that succeeds
                    for nd in helper.get_node_list(ssn.nodes):
                        try:
                            ssn.predicate_fn(_task, nd)
                        except FitFailure:
                            continue
                        yield nd
                candidates = _serial_feasible()
            for node in candidates:
                resreq = task.init_resreq.clone()
                reclaimed = Resource.empty()

                reclaimees: List = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is None:
                        continue
                    if j.queue != job.queue:
                        reclaimees.append(t.shared_clone())
                victims = (selector.victims(task, reclaimees)
                           if selector is not None
                           else ssn.reclaimable(task, reclaimees))
                if not victims:
                    continue

                all_res = Resource.empty()
                for v in victims:
                    all_res.add(v.resreq)
                if all_res.less(resreq):
                    continue

                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except (KeyError, RuntimeError) as e:
                        logger.error("Failed to reclaim %s/%s: %s",
                                     reclaimee.namespace, reclaimee.name, e)
                        continue
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break

                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    if view is not None:
                        view.on_pipeline(node.name, task)
                        if fell_back and view.needs_poison(task):
                            # affinity pod became resident (see preempt)
                            view.poison()
                    assigned = True
                    break

            if assigned:
                queues.push(queue)
