"""Action registry (volcano pkg/scheduler/actions/factory.go)."""

from volcano_tpu.scheduler.framework.plugins import register_action
from volcano_tpu.scheduler.actions.allocate import AllocateAction
from volcano_tpu.scheduler.actions.backfill import BackfillAction
from volcano_tpu.scheduler.actions.enqueue import EnqueueAction
from volcano_tpu.scheduler.actions.preempt import PreemptAction
from volcano_tpu.scheduler.actions.reclaim import ReclaimAction

register_action(AllocateAction())
register_action(BackfillAction())
register_action(EnqueueAction())
register_action(PreemptAction())
register_action(ReclaimAction())
