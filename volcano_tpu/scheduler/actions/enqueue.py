"""enqueue — admit Pending PodGroups into the cluster
(volcano pkg/scheduler/actions/enqueue/enqueue.go:42-124).

A PodGroup flips Pending->Inqueue when its MinResources fit within
1.2x cluster allocatable minus used (the overcommit factor, enqueue.go:80)
and every JobEnqueueable plugin agrees. Downstream, the admission pod-gate
only lets pods be created for Inqueue groups (delay-pod-creation design).
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api import objects
from volcano_tpu.api.resource import Resource
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util.priority_queue import PriorityQueue

OVERCOMMIT_FACTOR = 1.2


class EnqueueAction(Action):
    def name(self) -> str:
        return "enqueue"

    def execute(self, ssn) -> None:
        queues = PriorityQueue(cmp_fn=ssn.queue_order_cmp)
        queue_set = set()
        jobs_map: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                if job.queue not in jobs_map:
                    jobs_map[job.queue] = PriorityQueue(cmp_fn=ssn.job_order_cmp)
                jobs_map[job.queue].push(job)

        empty = Resource.empty()
        nodes_idle = Resource.empty()
        for node in ssn.nodes.values():
            nodes_idle.add(node.allocatable.clone().multi(OVERCOMMIT_FACTOR).sub(node.used))

        while not queues.empty():
            if nodes_idle.less(empty):
                break
            queue = queues.pop()
            jobs = jobs_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            inqueue = False
            if job.pod_group.spec.min_resources is None:
                inqueue = True
            else:
                pg_resource = Resource.from_resource_list(job.pod_group.spec.min_resources)
                if ssn.job_enqueueable(job) and pg_resource.less_equal(nodes_idle):
                    nodes_idle.sub(pg_resource)
                    inqueue = True

            if inqueue:
                job.pod_group.status.phase = objects.PodGroupPhase.INQUEUE
                ssn.jobs[job.uid] = job

            queues.push(queue)
