"""allocate — the primary placement action
(volcano pkg/scheduler/actions/allocate/allocate.go:42-247).

Stages: namespace PQ -> queue (linear scan with Overused filter) -> job PQ ->
task PQ -> predicate -> prioritize -> best node -> Allocate (fits idle) or
Pipeline (fits releasing); per-job Statement committed only when the gang is
JobReady, else discarded.

This serial loop is the parity oracle; the ``tpuscore`` plugin swaps the
per-task sweep for a batched TPU solve (volcano_tpu.ops) behind the same
Statement/commit gate.
"""

from __future__ import annotations

import logging
from typing import Dict

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import NODE_RESOURCE_FIT_FAILED, FitFailure
from volcano_tpu.scheduler.framework.interface import Action
from volcano_tpu.scheduler.util import scheduler_helper as helper
from volcano_tpu.scheduler.util.priority_queue import (
    PriorityQueue,
    make_task_queue,
)

logger = logging.getLogger(__name__)


def finish_batched(ssn, solver) -> None:
    """Post-bulk bookkeeping after a successful batched solve: residue
    profile keys + the serial residue pass. Shared by the per-action
    execute below and the session-fused driver (ops/session_fuse.py), so
    both land identical residue semantics and profile keys."""
    prof = solver.profile
    # residue-family keys are always present (0 when the serial
    # residue pass never ran) so bench consumers need no
    # existence checks
    prof.setdefault("residue_pass_ms", 0.0)
    prof.setdefault("residue_pass_tasks", 0)
    residue = prof.get("residue", 0)
    unplaced = prof.get("tasks", 0) - prof.get("placed", 0)
    if residue or (prof.get("has_releasing") and unplaced):
        # serial residue pass: tasks the device solve does not model
        # (pod affinity, host ports) are still PENDING, and nodes
        # with releasing capacity can still pipeline leftovers; the
        # serial loop picks up exactly the remaining pending tasks
        # on post-bulk state with full predicate fidelity. The dense
        # alloc assist (vectorized window + cached score rows, live
        # residual affinity/ports checks) replaces the per-node
        # closure sweeps with bit-identical selections.
        import time

        from volcano_tpu.ops import preemptview

        logger.info(
            "allocate: serial residue pass (%d residue tasks, "
            "%d unplaced)", residue, unplaced)
        t0 = time.perf_counter()
        AllocateAction()._serial_execute(
            ssn, assist=preemptview.build_alloc_assist(ssn))
        # the tail the device solve left to the host, as first-class
        # profile terms (bench: tpu_residue_ms / tpu_residue_tasks)
        # — the candidate-window straggler rounds exist to shrink
        # exactly these numbers
        prof["residue_pass_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        prof["residue_pass_tasks"] = residue + (
            unplaced if prof.get("has_releasing") else 0)


class AllocateAction(Action):
    def name(self) -> str:
        return "allocate"

    def execute(self, ssn) -> None:
        # TPU backend hook: if the tpuscore plugin attached a batch solver to
        # this session, let it drive placement for the whole snapshot; the
        # serial loop below remains the fallback and oracle.
        solver = getattr(ssn, "batch_allocator", None)
        if solver is not None and solver(ssn):
            finish_batched(ssn, solver)
            return
        self._serial_execute(ssn)

    def _serial_execute(self, ssn, assist=None) -> None:
        namespaces = PriorityQueue(cmp_fn=ssn.namespace_order_cmp)
        # namespace -> queue -> job PQ
        jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}

        for job in ssn.jobs.values():
            if job.pod_group.status.phase == objects.PodGroupPhase.PENDING:
                continue
            if not job.task_status_index.get(TaskStatus.PENDING):
                continue  # nothing to place or pipeline for this job
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            if job.queue not in ssn.queues:
                logger.warning(
                    "Skip adding Job <%s/%s>: queue %s not found",
                    job.namespace, job.name, job.queue)
                continue
            queue_map = jobs_map.get(job.namespace)
            if queue_map is None:
                namespaces.push(job.namespace)
                queue_map = jobs_map[job.namespace] = {}
            if job.queue not in queue_map:
                queue_map[job.queue] = PriorityQueue(cmp_fn=ssn.job_order_cmp)
            queue_map[job.queue].push(job)

        pending_tasks: Dict[str, PriorityQueue] = {}
        all_nodes = helper.get_node_list(ssn.nodes)

        def predicate_fn(task: TaskInfo, node) -> None:
            # resource fit against idle OR releasing, then plugin chain
            # (allocate.go:103-117)
            if not task.init_resreq.less_equal(node.idle) and not task.init_resreq.less_equal(node.releasing):
                raise FitFailure(NODE_RESOURCE_FIT_FAILED)
            ssn.predicate_fn(task, node)

        predicates = ssn.plugins.get("predicates") if assist is not None else None

        def _residual_for(task):
            """Live ports/affinity check closure for the assist's window,
            or None when the base mask already decides everything."""
            if predicates is None or not hasattr(predicates, "needs_residual"):
                return None
            if not predicates.needs_residual(task.pod):
                return None
            check = predicates.residual_check

            def residual(node) -> bool:
                try:
                    check(task, node)
                except FitFailure:
                    return False
                return True

            return residual

        while not namespaces.empty():
            namespace = namespaces.pop()
            queue_in_namespace = jobs_map[namespace]

            # linear queue scan with overused filter (allocate.go:134-146)
            queue = None
            for queue_id in list(queue_in_namespace):
                current = ssn.queues[queue_id]
                if ssn.overused(current):
                    del queue_in_namespace[queue_id]
                    continue
                if queue is None or ssn.queue_order_fn(current, queue):
                    queue = current
            if queue is None:
                continue

            jobs = queue_in_namespace.get(queue.uid)
            if jobs is None or jobs.empty():
                continue

            job: JobInfo = jobs.pop()
            if job.uid not in pending_tasks:
                pending_tasks[job.uid] = make_task_queue(ssn, [
                    task for task in job.task_status_index.get(
                        TaskStatus.PENDING, {}).values()
                    if not task.resreq.is_empty()  # BestEffort -> backfill
                ])
            tasks = pending_tasks[job.uid]

            stmt = ssn.statement()
            stmt_ops = []  # (hook_undo_kind, host, task) for assist unwind

            while not tasks.empty():
                task: TaskInfo = tasks.pop()

                if job.nodes_fit_delta:
                    job.nodes_fit_delta = {}

                node = None
                if assist is not None:
                    node = assist.alloc_best_node(task, _residual_for(task))
                if node is None:
                    found_nodes, fit_errors = helper.predicate_nodes(
                        task, all_nodes, predicate_fn)
                    if not found_nodes:
                        job.nodes_fit_errors[task.uid] = fit_errors
                        break

                    node_scores = helper.prioritize_nodes(
                        task, found_nodes,
                        ssn.batch_node_order_fn, ssn.node_order_map_fn,
                        ssn.node_order_reduce_fn)
                    node = helper.select_best_node(node_scores)

                if task.init_resreq.less_equal(node.idle):
                    try:
                        stmt.allocate(task, node.name)
                    except (KeyError, RuntimeError) as e:
                        logger.error("Failed to bind Task %s on %s: %s", task.uid, node.name, e)
                    else:
                        if assist is not None:
                            assist.on_allocate(node.name, task)
                            stmt_ops.append(("alloc", node.name, task))
                else:
                    # record the shortfall, then try releasing resources
                    delta = node.idle.clone()
                    delta.fit_delta(task.init_resreq)
                    job.nodes_fit_delta[node.name] = delta
                    if task.init_resreq.less_equal(node.releasing):
                        stmt.pipeline(task, node.name)
                        if assist is not None:
                            assist.on_pipeline_alloc(node.name, task)
                            stmt_ops.append(("pipe", node.name, task))

                if ssn.job_ready(job):
                    jobs.push(job)
                    break

            if ssn.job_ready(job):
                stmt.commit()
            else:
                stmt.discard()
                if assist is not None:
                    # mirror the statement rollback in the assist's matrices
                    for kind, host, t in reversed(stmt_ops):
                        if kind == "alloc":
                            assist.on_unallocate(host, t)
                        else:
                            assist.on_unpipeline_alloc(host, t)

            namespaces.push(namespace)
