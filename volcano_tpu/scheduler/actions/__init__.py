"""Scheduling actions, run in configured order each session
(volcano pkg/scheduler/actions)."""

from volcano_tpu.scheduler.actions import factory  # noqa: F401  (registers all)
