"""tpuscore — the TPU batch-solve gate (BASELINE.json north star).

A session plugin (reference seam: volcano pkg/scheduler/framework/plugins.go
RegisterPluginBuilder) that attaches a BatchAllocator to the session; the
allocate action (actions/allocate.py) hands the whole placement pass to it
and keeps the serial loop as fallback/oracle. With the plugin absent or
``tpuscore.enable: "false"``, scheduling behavior is bit-identical to the
serial path — the plugin API is the gate, exactly as the reference's design
demands (the Go hot loop unchanged when the backend is off).

Arguments:
    tpuscore.enable: "true"/"false" (default true)
    tpuscore.dtype:  "float32"/"float64" (default: float64 under jax x64,
                     float32 otherwise; bf16 is rejected — memory-byte
                     epsilons need >8 mantissa bits)
    tpuscore.mode:   "parity"/"rounds"/"auto" (default auto — rounds for
                     large sessions, parity-scan for small; see
                     ops/solver.py BatchAllocator)
"""

from __future__ import annotations

import logging

import numpy as np

from volcano_tpu.scheduler.framework.interface import Plugin

logger = logging.getLogger(__name__)

PLUGIN_NAME = "tpuscore"

ENABLE = "tpuscore.enable"
DTYPE = "tpuscore.dtype"
MODE = "tpuscore.mode"

_DTYPES = {"float32": np.float32, "float64": np.float64}

# driver-installed default mesh: the scheduler driver calls set_default_mesh
# once at startup so every session's plugin instance (rebuilt each cycle by
# open_session) shards over it without post-open patching
_DEFAULT_MESH = None


def set_default_mesh(mesh) -> None:
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def mesh_fingerprint():
    """Hashable identity of the driver-installed default mesh (device
    count + shard spec + device ids), or None without one. Part of the
    pipeline speculation fingerprint: a speculative solve sealed under one
    mesh shape is mis-sharded for any other — the stage must discard, not
    apply (pipeline/driver.py, ``pipeline_spec_discard{reason="mesh"}``)."""
    m = _DEFAULT_MESH
    if m is None:
        return None
    return (tuple(m.shape.items()),
            tuple(int(d.id) for d in m.devices.ravel()))


class TpuScorePlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.profile: dict = {}
        self.mesh = _DEFAULT_MESH  # per-instance override allowed

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.framework.arguments import Arguments

        args = self.arguments if isinstance(self.arguments, Arguments) else Arguments(self.arguments)
        if not args.get_bool(ENABLE, True):
            return
        from volcano_tpu.ops.solver import BatchAllocator

        requested = str(args.get(DTYPE, ""))
        dtype = _DTYPES.get(requested)
        if requested and dtype is None:
            logger.warning(
                "tpuscore.dtype %r not supported (%s); using platform default",
                requested, "/".join(_DTYPES),
            )
        mode = str(args.get(MODE, "auto")) or "auto"
        if mode not in ("auto", "parity", "rounds"):
            logger.warning(
                "tpuscore.mode %r not supported (auto/parity/rounds); using auto",
                mode,
            )
            mode = "auto"
        ssn.batch_allocator = BatchAllocator(
            mesh=self.mesh, dtype=dtype, profile=self.profile, mode=mode
        )

    def on_session_close(self, ssn) -> None:
        if getattr(ssn, "batch_allocator", None) is not None:
            ssn.batch_allocator = None


def new(arguments):
    return TpuScorePlugin(arguments)
