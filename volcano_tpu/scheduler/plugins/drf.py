"""drf — Dominant Resource Fairness ordering and preemption policy
(volcano pkg/scheduler/plugins/drf/drf.go).

share(job) = max_r allocated_r / total_r (drf.go:299-311). Job order prefers
the smaller share; preemption only when the preemptor's post-allocation share
stays below the victim's post-eviction share; optional weighted namespace
order. Event handlers keep shares incremental as the session allocates/evicts.
"""

from __future__ import annotations

import math
from typing import Dict, List

from volcano_tpu.api.resource import Resource

from volcano_tpu.scheduler import conf
from volcano_tpu.scheduler.framework.event_handlers import EventHandler
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "drf"
SHARE_DELTA = 0.000001


class _Attr:
    __slots__ = ("share", "dominant_resource", "allocated")

    def __init__(self):
        self.share = 0.0
        self.dominant_resource = ""
        self.allocated = Resource.empty()


class DrfPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total_resource = Resource.empty()
        self.job_attrs: Dict[str, _Attr] = {}
        self.namespace_opts: Dict[str, _Attr] = {}
        self._total_pairs = None  # (total, [(name, value)]) memo

    def name(self) -> str:
        return PLUGIN_NAME

    def _namespace_order_enabled(self, ssn) -> bool:
        for tier in ssn.tiers:
            for plugin in tier.plugins:
                if plugin.name == PLUGIN_NAME:
                    return conf.enabled(plugin.enabled_namespace_order)
        return False

    def _calculate_share(self, allocated: Resource, total: Resource):
        # total is static after on_session_open; its (name, value) pairs
        # are materialized once — this runs per task transition event in
        # the preempt/reclaim hot loops
        pairs = self._total_pairs
        if pairs is None or pairs[0] is not total:
            pairs = self._total_pairs = (
                total, [(rn, total.get(rn)) for rn in total.resource_names()])
        res, dominant = 0.0, ""
        get = allocated.get
        for rn, tv in pairs[1]:
            l = get(rn)
            s = ((0.0 if l == 0 else 1.0) if tv == 0 else l / tv)
            if s > res:
                res = s
                dominant = rn
        return dominant, res

    def _update_share(self, attr: _Attr) -> None:
        attr.dominant_resource, attr.share = self._calculate_share(
            attr.allocated, self.total_resource
        )

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.cache.nodeaxis import add_total_allocatable

        add_total_allocatable(ssn, self.total_resource)

        namespace_order_enabled = self._namespace_order_enabled(ssn)

        job_attrs = self.job_attrs
        for job in ssn.jobs.values():
            attr = _Attr()
            # job.allocated is the incrementally-maintained sum over the
            # allocated-status buckets — identical to the per-task walk
            # (drf.go:84-90) at O(1) per job
            alloc = job.allocated
            if alloc.milli_cpu == 0.0 and alloc.memory == 0.0 and \
                    not any((alloc.scalar_resources or {}).values()):
                # exactly-zero allocation: share is 0 with no dominant
                # resource, which is _Attr()'s initial state — skip the
                # copy and the share scan (the common all-pending regime)
                job_attrs[job.uid] = attr
            else:
                attr.allocated.add(alloc)
                self._update_share(attr)
                job_attrs[job.uid] = attr

            if namespace_order_enabled:
                ns_opt = self.namespace_opts.setdefault(job.namespace, _Attr())
                ns_opt.allocated.add(attr.allocated)
                self._update_share(ns_opt)

        def preemptable_fn(preemptor, preemptees: List) -> List:
            victims = []

            if namespace_order_enabled:
                # namespace-level weighted-share policy first (drf.go:120-178)
                l_ns_info = ssn.namespace_info.get(preemptor.namespace)
                l_weight = l_ns_info.get_weight() if l_ns_info else 1
                l_ns_att = self.namespace_opts[preemptor.namespace]
                l_alloc = l_ns_att.allocated.clone().add(preemptor.resreq)
                _, l_share = self._calculate_share(l_alloc, self.total_resource)
                l_weighted = l_share / l_weight

                namespace_allocation: Dict[str, Resource] = {}
                undecided = []
                for preemptee in preemptees:
                    if preemptor.namespace == preemptee.namespace:
                        undecided.append(preemptee)
                        continue
                    ns_alloc = namespace_allocation.get(preemptee.namespace)
                    if ns_alloc is None:
                        r_att = self.namespace_opts[preemptee.namespace]
                        ns_alloc = r_att.allocated.clone()
                        namespace_allocation[preemptee.namespace] = ns_alloc
                    r_ns_info = ssn.namespace_info.get(preemptee.namespace)
                    r_weight = r_ns_info.get_weight() if r_ns_info else 1
                    r_alloc = ns_alloc.sub(preemptee.resreq)
                    _, r_share = self._calculate_share(r_alloc, self.total_resource)
                    r_weighted = r_share / r_weight
                    if l_weighted < r_weighted:
                        victims.append(preemptee)
                    if l_weighted - r_weighted > SHARE_DELTA:
                        continue
                    undecided.append(preemptee)
                preemptees = undecided

            l_att = self.job_attrs[preemptor.job]
            l_alloc = l_att.allocated.clone().add(preemptor.resreq)
            _, ls = self._calculate_share(l_alloc, self.total_resource)

            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    allocations[preemptee.job] = self.job_attrs[preemptee.job].allocated.clone()
                r_alloc = allocations[preemptee.job].sub(preemptee.resreq)
                _, rs = self._calculate_share(r_alloc, self.total_resource)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(PLUGIN_NAME, preemptable_fn)

        def job_order_fn(l, r) -> int:
            l_share = self.job_attrs[l.uid].share
            r_share = self.job_attrs[r.uid].share
            if l_share == r_share:
                return 0
            return -1 if l_share < r_share else 1

        ssn.add_job_order_fn(PLUGIN_NAME, job_order_fn)

        if namespace_order_enabled:
            def namespace_order_fn(l: str, r: str) -> int:
                l_opt = self.namespace_opts.get(l) or _Attr()
                r_opt = self.namespace_opts.get(r) or _Attr()
                li = ssn.namespace_info.get(l)
                ri = ssn.namespace_info.get(r)
                lw = li.get_weight() if li else 1
                rw = ri.get_weight() if ri else 1
                lws, rws = l_opt.share / lw, r_opt.share / rw
                if lws == rws:
                    return 0
                return -1 if lws < rws else 1

            ssn.add_namespace_order_fn(PLUGIN_NAME, namespace_order_fn)

        def on_allocate(event) -> None:
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)
            if namespace_order_enabled:
                ns_opt = self.namespace_opts[event.task.namespace]
                ns_opt.allocated.add(event.task.resreq)
                self._update_share(ns_opt)

        def on_deallocate(event) -> None:
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)
            if namespace_order_enabled:
                ns_opt = self.namespace_opts[event.task.namespace]
                ns_opt.allocated.sub(event.task.resreq)
                self._update_share(ns_opt)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate, deallocate_func=on_deallocate,
                         origin=(PLUGIN_NAME, self, namespace_order_enabled))
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.job_attrs = {}


def new(arguments):
    return DrfPlugin(arguments)
