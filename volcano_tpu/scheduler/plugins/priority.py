"""priority — task/job ordering by pod priority
(volcano pkg/scheduler/plugins/priority/priority.go:43-84)."""

from __future__ import annotations

from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "priority"


class PriorityPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def task_order_fn(l, r) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        # key twin of the comparator: higher priority sorts first
        ssn.add_task_order_fn(PLUGIN_NAME, task_order_fn,
                              key=lambda t: -t.priority)

        def job_order_fn(l, r) -> int:
            if l.priority > r.priority:
                return -1
            if l.priority < r.priority:
                return 1
            return 0

        ssn.add_job_order_fn(PLUGIN_NAME, job_order_fn)


def new(arguments):
    return PriorityPlugin(arguments)
