"""Plugin registry (volcano pkg/scheduler/plugins/factory.go:33-45)."""

from volcano_tpu.scheduler.framework.plugins import register_plugin_builder
from volcano_tpu.scheduler.plugins import (
    binpack,
    conformance,
    drf,
    gang,
    nodeorder,
    predicates,
    priority,
    proportion,
    tpuscore,
)

register_plugin_builder("gang", gang.new)
register_plugin_builder("priority", priority.new)
register_plugin_builder("conformance", conformance.new)
register_plugin_builder("drf", drf.new)
register_plugin_builder("proportion", proportion.new)
register_plugin_builder("predicates", predicates.new)
register_plugin_builder("nodeorder", nodeorder.new)
register_plugin_builder("binpack", binpack.new)
register_plugin_builder("tpuscore", tpuscore.new)
