"""Per-plugin enable-flag defaulting
(volcano pkg/scheduler/plugins/defaults.go:24). The implementation lives in
scheduler.conf so the framework can default options without importing the
plugin package."""

from volcano_tpu.scheduler.conf import apply_plugin_conf_defaults  # noqa: F401
