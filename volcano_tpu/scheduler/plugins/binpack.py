"""binpack — best-fit node scoring
(volcano pkg/scheduler/plugins/binpack/binpack.go).

score = (sum_r w_r * (request_r + used_r)/capacity_r) / sum(w) * 10 * weight,
with per-resource weights (incl. arbitrary scalar resources) from plugin
arguments (binpack.go:95-152, 201-261).
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "binpack"

BINPACK_WEIGHT = "binpack.weight"
BINPACK_CPU = "binpack.cpu"
BINPACK_MEMORY = "binpack.memory"
BINPACK_RESOURCES = "binpack.resources"
BINPACK_RESOURCES_PREFIX = BINPACK_RESOURCES + "."

MAX_PRIORITY = 10


class PriorityWeight:
    def __init__(self, weight=1, cpu=1, memory=1, resources=None):
        self.binpacking_weight = weight
        self.binpacking_cpu = cpu
        self.binpacking_memory = memory
        self.binpacking_resources: Dict[str, int] = resources or {}


def calculate_weight(args) -> PriorityWeight:
    from volcano_tpu.scheduler.framework.arguments import Arguments

    args = args if isinstance(args, Arguments) else Arguments(args or {})
    w = PriorityWeight()
    w.binpacking_weight = args.get_int(BINPACK_WEIGHT, 1)
    w.binpacking_cpu = args.get_int(BINPACK_CPU, 1)
    if w.binpacking_cpu < 0:
        w.binpacking_cpu = 1
    w.binpacking_memory = args.get_int(BINPACK_MEMORY, 1)
    if w.binpacking_memory < 0:
        w.binpacking_memory = 1
    for resource in str(args.get(BINPACK_RESOURCES, "")).split(","):
        resource = resource.strip()
        if not resource:
            continue
        rw = args.get_int(BINPACK_RESOURCES_PREFIX + resource, 1)
        if rw < 0:
            rw = 1
        w.binpacking_resources[resource] = rw
    return w


def resource_bin_packing_score(requested: float, capacity: float, used: float, weight: int) -> float:
    """(binpack.go:249-261)"""
    if capacity == 0 or weight == 0:
        return 0.0
    used_finally = requested + used
    if used_finally > capacity:
        return 0.0
    return used_finally * weight / capacity


def bin_packing_score(task: TaskInfo, node: NodeInfo, weight: PriorityWeight) -> float:
    """(binpack.go:201-246)"""
    score = 0.0
    weight_sum = 0
    requested = task.resreq
    for resource in requested.resource_names():
        request = requested.get(resource)
        if request == 0:
            continue
        if resource == "cpu":
            resource_weight = weight.binpacking_cpu
        elif resource == "memory":
            resource_weight = weight.binpacking_memory
        elif resource in weight.binpacking_resources:
            resource_weight = weight.binpacking_resources[resource]
        else:
            continue
        score += resource_bin_packing_score(
            request, node.allocatable.get(resource), node.used.get(resource), resource_weight
        )
        weight_sum += resource_weight

    if weight_sum > 0:
        score /= weight_sum
    return score * MAX_PRIORITY * weight.binpacking_weight


class BinpackPlugin(Plugin):
    def __init__(self, arguments=None):
        self.weight = calculate_weight(arguments)

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        if self.weight.binpacking_weight == 0:
            return
        ssn.add_node_order_fn(
            PLUGIN_NAME, lambda task, node: bin_packing_score(task, node, self.weight)
        )


def new(arguments):
    return BinpackPlugin(arguments)
