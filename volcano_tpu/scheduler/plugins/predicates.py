"""predicates — node feasibility checks
(volcano pkg/scheduler/plugins/predicates/predicates.go).

The reference chains upstream k8s predicate functions over a parallel
``cache.NodeInfo`` map it maintains with event handlers; here the same checks
are implemented natively over the session's NodeInfo (whose task set the
session keeps current through allocate/evict), in the same order:

pod count -> node condition -> unschedulable -> node selector (+ required
node affinity) -> host ports -> taints/tolerations -> optional memory/disk/
pid pressure -> pod (anti-)affinity with required-term symmetry.

Each failure raises FitFailure with reason strings matching upstream phrasing
so fit-error histograms are comparable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import FitFailure
from volcano_tpu.scheduler.framework.event_handlers import EventHandler
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "predicates"

MEMORY_PRESSURE_PREDICATE = "predicate.MemoryPressureEnable"
DISK_PRESSURE_PREDICATE = "predicate.DiskPressureEnable"
PID_PRESSURE_PREDICATE = "predicate.PIDPressureEnable"

NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"

HOSTNAME_TOPOLOGY_KEY = "kubernetes.io/hostname"


def _node_topology_value(node: NodeInfo, key: str) -> str:
    labels = node.node.metadata.labels if node.node is not None else {}
    if key == HOSTNAME_TOPOLOGY_KEY and key not in labels:
        return node.name
    return labels.get(key, "")


def _pods_on_node(node: NodeInfo) -> List[objects.Pod]:
    return [t.pod for t in node.tasks.values() if t.pod is not None]


def _selector_matches_pod(term: objects.PodAffinityTerm, pod: objects.Pod, incoming_ns: str) -> bool:
    namespaces = term.namespaces or [incoming_ns]
    if pod.metadata.namespace not in namespaces:
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(pod.metadata.labels)


def pod_matches_node_selector(pod: objects.Pod, node: NodeInfo) -> bool:
    """nodeSelector AND required node-affinity terms (PodMatchNodeSelector)."""
    labels = node.node.metadata.labels if node.node is not None else {}
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    affinity = pod.spec.affinity
    if affinity is not None and affinity.node_affinity is not None:
        required = affinity.node_affinity.required_terms
        if required and not any(term.matches(labels) for term in required):
            return False
    return True


def tolerates_taints(pod: objects.Pod, node: NodeInfo) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (PodToleratesNodeTaints)."""
    if node.node is None:
        return True
    for taint in node.node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue  # PreferNoSchedule never blocks
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            return False
    return True


def host_ports_free(pod: objects.Pod, node: NodeInfo) -> bool:
    wanted = {
        (p.host_port, p.protocol)
        for c in pod.spec.containers
        for p in c.ports
        if p.host_port > 0
    }
    if not wanted:
        return True
    for existing in _pods_on_node(node):
        for c in existing.spec.containers:
            for p in c.ports:
                if p.host_port > 0 and (p.host_port, p.protocol) in wanted:
                    return False
    return True


def _affinity_term_satisfied(term: objects.PodAffinityTerm, pod: objects.Pod,
                             node: NodeInfo, all_nodes: List[NodeInfo]) -> bool:
    """Some existing pod matching the selector runs in the node's topology
    domain for term.topology_key."""
    my_topo = _node_topology_value(node, term.topology_key)
    for other in all_nodes:
        if _node_topology_value(other, term.topology_key) != my_topo:
            continue
        for existing in _pods_on_node(other):
            if _selector_matches_pod(term, existing, pod.metadata.namespace):
                return True
    return False


def _anti_affinity_violated(term: objects.PodAffinityTerm, pod: objects.Pod,
                            node: NodeInfo, all_nodes: List[NodeInfo]) -> bool:
    return _affinity_term_satisfied(term, pod, node, all_nodes)


def _term_matches_no_pod_but_self(term: objects.PodAffinityTerm, pod: objects.Pod,
                                  all_nodes: List[NodeInfo]) -> bool:
    """Upstream carve-out (vendored predicates.go:1380-1389): a required
    affinity term that matches NO existing pod anywhere is allowed when the
    incoming pod matches its own selector — so the first pod of a
    self-affine gang can land."""
    for other in all_nodes:
        for existing in _pods_on_node(other):
            if _selector_matches_pod(term, existing, pod.metadata.namespace):
                return False
    return _selector_matches_pod(term, pod, pod.metadata.namespace)


def _has_required_anti_affinity(pod: Optional[objects.Pod]) -> bool:
    if pod is None or pod.spec.affinity is None:
        return False
    anti = pod.spec.affinity.pod_anti_affinity
    return anti is not None and bool(anti.required_terms)


def pod_affinity_fits(
    pod: objects.Pod,
    node: NodeInfo,
    all_nodes: List[NodeInfo],
    anti_resident: Optional[Dict[str, Tuple[objects.Pod, str]]] = None,
    nodes_by_name: Optional[Dict[str, NodeInfo]] = None,
) -> bool:
    """(Anti-)affinity of the incoming pod plus required-term symmetry of
    existing pods. ``anti_resident`` (uid -> (pod, node_name)), when given,
    is an exact mirror of the pods with required anti-affinity currently on
    any node — the only pods the symmetry clause can match — letting the
    common no-anti-affinity session skip the O(nodes x pods) sweep the
    reference sidesteps with its affinity-only PodLister fast path
    (plugins/util/util.go:34-57)."""
    affinity = pod.spec.affinity
    if affinity is not None:
        if affinity.pod_affinity is not None:
            for term in affinity.pod_affinity.required_terms:
                if not _affinity_term_satisfied(term, pod, node, all_nodes) and \
                        not _term_matches_no_pod_but_self(term, pod, all_nodes):
                    return False
        if affinity.pod_anti_affinity is not None:
            for term in affinity.pod_anti_affinity.required_terms:
                if _anti_affinity_violated(term, pod, node, all_nodes):
                    return False
    # symmetry: existing pods' required anti-affinity must not match us
    if anti_resident is not None and nodes_by_name is not None:
        for existing, node_name in anti_resident.values():
            other = nodes_by_name.get(node_name)
            if other is None:
                continue
            for term in existing.spec.affinity.pod_anti_affinity.required_terms:
                if not _selector_matches_pod(term, pod, existing.metadata.namespace):
                    continue
                topo = term.topology_key
                if _node_topology_value(node, topo) == _node_topology_value(other, topo):
                    return False
        return True
    for other in all_nodes:
        for existing in _pods_on_node(other):
            ea = existing.spec.affinity
            if ea is None or ea.pod_anti_affinity is None:
                continue
            for term in ea.pod_anti_affinity.required_terms:
                if not _selector_matches_pod(term, pod, existing.metadata.namespace):
                    continue
                topo = term.topology_key
                if _node_topology_value(node, topo) == _node_topology_value(other, topo):
                    return False
    return True


def _node_condition(node: NodeInfo, cond_type: str) -> bool:
    if node.node is None:
        return False
    for cond in node.node.status.conditions:
        if cond.type == cond_type:
            return cond.status == "True"
    return False


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.framework.arguments import Arguments

        args = self.arguments if isinstance(self.arguments, Arguments) else Arguments(self.arguments)
        memory_pressure = args.get_bool(MEMORY_PRESSURE_PREDICATE, False)
        disk_pressure = args.get_bool(DISK_PRESSURE_PREDICATE, False)
        pid_pressure = args.get_bool(PID_PRESSURE_PREDICATE, False)

        # The node set is fixed for the session; build the list once instead
        # of per predicate call (the serial sweep calls this O(tasks x nodes)
        # times).
        all_nodes = list(ssn.nodes.values())

        # anti_resident mirrors {pods with required anti-affinity currently
        # in some node's task map}. Maintained through session events:
        # allocate/pipeline add the task to a node; unallocate/unpipeline
        # remove it; evict fires deallocate but leaves the task on the node
        # as RELEASING (statement.py evict), so RELEASING deallocations are
        # kept. Bulk-applied placements (ops/solver._apply_bulk) never carry
        # (anti-)affinity — the encoder routes those tasks to the serial
        # residue pass — so bypassing the event machinery cannot stale this
        # index.
        anti_resident: Dict[str, Tuple[objects.Pod, str]] = {}
        for _node in all_nodes:
            for _t in _node.tasks.values():
                if _has_required_anti_affinity(_t.pod):
                    anti_resident[_t.uid] = (_t.pod, _node.name)

        def _track_allocate(event) -> None:
            t = event.task
            if _has_required_anti_affinity(t.pod) and t.node_name:
                anti_resident[t.uid] = (t.pod, t.node_name)

        def _track_deallocate(event) -> None:
            t = event.task
            if _has_required_anti_affinity(t.pod) and t.status != TaskStatus.RELEASING:
                anti_resident.pop(t.uid, None)

        ssn.add_event_handler(EventHandler(_track_allocate, _track_deallocate))

        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            pod = task.pod
            if pod is None:
                return

            # pod count (predicates.go:165)
            if node.allocatable.max_task_num <= len(node.tasks):
                raise FitFailure(NODE_POD_NUMBER_EXCEEDED)

            # node conditions (CheckNodeConditionPredicate)
            if not _node_condition(node, "Ready"):
                raise FitFailure("node(s) were not ready")
            if _node_condition(node, "NetworkUnavailable"):
                raise FitFailure("node(s) had network unavailable")

            # unschedulable spec (CheckNodeUnschedulablePredicate)
            if node.node is not None and node.node.spec.unschedulable:
                raise FitFailure("node(s) were unschedulable")

            # node selector + required node affinity
            if not pod_matches_node_selector(pod, node):
                raise FitFailure("node(s) didn't match node selector")

            # host ports
            if not host_ports_free(pod, node):
                raise FitFailure("node(s) didn't have free ports for the requested pod ports")

            # taints
            if not tolerates_taints(pod, node):
                raise FitFailure("node(s) had taints that the pod didn't tolerate")

            if memory_pressure and _node_condition(node, "MemoryPressure"):
                raise FitFailure("node(s) had memory pressure")
            if disk_pressure and _node_condition(node, "DiskPressure"):
                raise FitFailure("node(s) had disk pressure")
            if pid_pressure and _node_condition(node, "PIDPressure"):
                raise FitFailure("node(s) had pid pressure")

            # pod (anti-)affinity incl. required-term symmetry
            if (pod.spec.affinity is not None or anti_resident) and \
                    not pod_affinity_fits(pod, node, all_nodes,
                                          anti_resident, ssn.nodes):
                raise FitFailure("node(s) didn't match pod affinity/anti-affinity")

        ssn.add_predicate_fn(PLUGIN_NAME, predicate_fn)


def new(arguments):
    return PredicatesPlugin(arguments)
