"""predicates — node feasibility checks
(volcano pkg/scheduler/plugins/predicates/predicates.go).

The reference chains upstream k8s predicate functions over a parallel
``cache.NodeInfo`` map it maintains with event handlers; here the same checks
are implemented natively over the session's NodeInfo (whose task set the
session keeps current through allocate/evict), in the same order:

pod count -> node condition -> unschedulable -> node selector (+ required
node affinity) -> host ports -> taints/tolerations -> optional memory/disk/
pid pressure -> pod (anti-)affinity with required-term symmetry.

Each failure raises FitFailure with reason strings matching upstream phrasing
so fit-error histograms are comparable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.unschedule_info import FitFailure
from volcano_tpu.scheduler.framework.event_handlers import EventHandler
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "predicates"

MEMORY_PRESSURE_PREDICATE = "predicate.MemoryPressureEnable"
DISK_PRESSURE_PREDICATE = "predicate.DiskPressureEnable"
PID_PRESSURE_PREDICATE = "predicate.PIDPressureEnable"

NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"

HOSTNAME_TOPOLOGY_KEY = "kubernetes.io/hostname"


def _node_topology_value(node: NodeInfo, key: str) -> str:
    labels = node.node.metadata.labels if node.node is not None else {}
    if key == HOSTNAME_TOPOLOGY_KEY and key not in labels:
        return node.name
    return labels.get(key, "")


def _pods_on_node(node: NodeInfo) -> List[objects.Pod]:
    return [t.pod for t in node.tasks.values() if t.pod is not None]


def _selector_matches_pod(term: objects.PodAffinityTerm, pod: objects.Pod, incoming_ns: str) -> bool:
    namespaces = term.namespaces or [incoming_ns]
    if pod.metadata.namespace not in namespaces:
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(pod.metadata.labels)


def pod_matches_node_selector(pod: objects.Pod, node: NodeInfo) -> bool:
    """nodeSelector AND required node-affinity terms (PodMatchNodeSelector)."""
    labels = node.node.metadata.labels if node.node is not None else {}
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    affinity = pod.spec.affinity
    if affinity is not None and affinity.node_affinity is not None:
        required = affinity.node_affinity.required_terms
        if required and not any(term.matches(labels) for term in required):
            return False
    return True


def tolerates_taints(pod: objects.Pod, node: NodeInfo) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (PodToleratesNodeTaints)."""
    if node.node is None:
        return True
    for taint in node.node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue  # PreferNoSchedule never blocks
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            return False
    return True


def host_ports_free(pod: objects.Pod, node: NodeInfo) -> bool:
    wanted = {
        (p.host_port, p.protocol)
        for c in pod.spec.containers
        for p in c.ports
        if p.host_port > 0
    }
    if not wanted:
        return True
    for existing in _pods_on_node(node):
        for c in existing.spec.containers:
            for p in c.ports:
                if p.host_port > 0 and (p.host_port, p.protocol) in wanted:
                    return False
    return True


def _affinity_term_satisfied(term: objects.PodAffinityTerm, pod: objects.Pod,
                             node: NodeInfo, all_nodes: List[NodeInfo],
                             domains=None, node_has_match=None) -> bool:
    """Some existing pod matching the selector runs in the node's topology
    domain for term.topology_key.

    ``domains`` (a callable key -> {value: [nodes]}, see the plugin's
    session-scoped index) restricts the sweep to the candidate node's OWN
    domain instead of re-filtering every node per call — the difference
    between O(domain) and the reference's O(pods x nodes) hot spot
    (predicates.go:281-299). Verdicts are identical: the domain list IS
    the set the full sweep's topology filter admits."""
    my_topo = _node_topology_value(node, term.topology_key)
    if domains is not None:
        others = domains(term.topology_key).get(my_topo, ())
    else:
        others = [o for o in all_nodes
                  if _node_topology_value(o, term.topology_key) == my_topo]
    for other in others:
        if node_has_match is not None:
            # label-pair index verdict: True/False are exact; None means
            # the index cannot decide (match_expressions, or a multi-pair
            # conjunction whose pairs all exist) and the pod scan runs
            r = node_has_match(term, pod.metadata.namespace, other)
            if r is True:
                return True
            if r is False:
                continue
        for existing in _pods_on_node(other):
            if _selector_matches_pod(term, existing, pod.metadata.namespace):
                return True
    return False


def _anti_affinity_violated(term: objects.PodAffinityTerm, pod: objects.Pod,
                            node: NodeInfo, all_nodes: List[NodeInfo],
                            domains=None, node_has_match=None) -> bool:
    return _affinity_term_satisfied(term, pod, node, all_nodes, domains,
                                    node_has_match)


def _term_matches_no_pod_but_self(term: objects.PodAffinityTerm, pod: objects.Pod,
                                  all_nodes: List[NodeInfo]) -> bool:
    """Upstream carve-out (vendored predicates.go:1380-1389): a required
    affinity term that matches NO existing pod anywhere is allowed when the
    incoming pod matches its own selector — so the first pod of a
    self-affine gang can land."""
    for other in all_nodes:
        for existing in _pods_on_node(other):
            if _selector_matches_pod(term, existing, pod.metadata.namespace):
                return False
    return _selector_matches_pod(term, pod, pod.metadata.namespace)


def _has_required_anti_affinity(pod: Optional[objects.Pod]) -> bool:
    if pod is None or pod.spec.affinity is None:
        return False
    anti = pod.spec.affinity.pod_anti_affinity
    return anti is not None and bool(anti.required_terms)


def pod_affinity_fits(
    pod: objects.Pod,
    node: NodeInfo,
    all_nodes: List[NodeInfo],
    anti_resident: Optional[Dict[str, Tuple[objects.Pod, str]]] = None,
    nodes_by_name: Optional[Dict[str, NodeInfo]] = None,
    domains=None,
    sym_excluded=None,
    node_has_match=None,
) -> bool:
    """(Anti-)affinity of the incoming pod plus required-term symmetry of
    existing pods. ``anti_resident`` (uid -> (pod, node_name)), when given,
    is an exact mirror of the pods with required anti-affinity currently on
    any node — the only pods the symmetry clause can match — letting the
    common no-anti-affinity session skip the O(nodes x pods) sweep the
    reference sidesteps with its affinity-only PodLister fast path
    (plugins/util/util.go:34-57). ``domains``/``sym_excluded`` (see the
    plugin) turn the remaining per-(pod, node) sweeps into domain-local
    scans and a set lookup — same verdicts, session-scale cost."""
    affinity = pod.spec.affinity
    if affinity is not None:
        if affinity.pod_affinity is not None:
            for term in affinity.pod_affinity.required_terms:
                if not _affinity_term_satisfied(term, pod, node, all_nodes,
                                                domains, node_has_match) and \
                        not _term_matches_no_pod_but_self(term, pod, all_nodes):
                    return False
        if affinity.pod_anti_affinity is not None:
            for term in affinity.pod_anti_affinity.required_terms:
                if _anti_affinity_violated(term, pod, node, all_nodes,
                                           domains, node_has_match):
                    return False
    if sym_excluded is not None:
        # precomputed per-pod exclusion domains (matching residents'
        # required anti-affinity terms): node rejected iff it sits in one
        for topo, val in sym_excluded:
            if _node_topology_value(node, topo) == val:
                return False
        return True
    # symmetry: existing pods' required anti-affinity must not match us
    if anti_resident is not None and nodes_by_name is not None:
        for existing, node_name in anti_resident.values():
            other = nodes_by_name.get(node_name)
            if other is None:
                continue
            for term in existing.spec.affinity.pod_anti_affinity.required_terms:
                if not _selector_matches_pod(term, pod, existing.metadata.namespace):
                    continue
                topo = term.topology_key
                if _node_topology_value(node, topo) == _node_topology_value(other, topo):
                    return False
        return True
    for other in all_nodes:
        for existing in _pods_on_node(other):
            ea = existing.spec.affinity
            if ea is None or ea.pod_anti_affinity is None:
                continue
            for term in ea.pod_anti_affinity.required_terms:
                if not _selector_matches_pod(term, pod, existing.metadata.namespace):
                    continue
                topo = term.topology_key
                if _node_topology_value(node, topo) == _node_topology_value(other, topo):
                    return False
    return True


def _node_condition(node: NodeInfo, cond_type: str) -> bool:
    if node.node is None:
        return False
    for cond in node.node.status.conditions:
        if cond.type == cond_type:
            return cond.status == "True"
    return False


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.framework.arguments import Arguments

        args = self.arguments if isinstance(self.arguments, Arguments) else Arguments(self.arguments)
        memory_pressure = args.get_bool(MEMORY_PRESSURE_PREDICATE, False)
        disk_pressure = args.get_bool(DISK_PRESSURE_PREDICATE, False)
        pid_pressure = args.get_bool(PID_PRESSURE_PREDICATE, False)

        # The node set is fixed for the session; build the list once instead
        # of per predicate call (the serial sweep calls this O(tasks x nodes)
        # times).
        all_nodes = list(ssn.nodes.values())

        # anti_resident mirrors {pods with required anti-affinity currently
        # in some node's task map}. Maintained through session events:
        # allocate/pipeline add the task to a node; unallocate/unpipeline
        # remove it; evict fires deallocate but leaves the task on the node
        # as RELEASING (statement.py evict), so RELEASING deallocations are
        # kept. Bulk-applied placements (ops/solver._apply_bulk) never carry
        # (anti-)affinity — the encoder routes those tasks to the serial
        # residue pass — so bypassing the event machinery cannot stale this
        # index.
        anti_resident: Dict[str, Tuple[objects.Pod, str]] = {}
        # inverted symmetry index over the residents' required anti terms:
        # a single-kv match_labels term excludes its node's topology domain
        # for every incoming pod carrying that (scope-ns, k, v) label —
        # sym_single[(ns, k, v)] refcounts {(topo_key, topo_val): n}.
        # Terms the index cannot represent (multi-kv, match_expressions,
        # selector-less) stay in sym_complex[uid] for the per-pod scan.
        # Together they turn the per-incoming-pod symmetry sweep from
        # O(residents) selector matches into O(pod labels) dict lookups.
        sym_single: Dict[tuple, Dict[tuple, int]] = {}
        sym_complex: Dict[str, list] = {}

        def _sym_single_entries(pod: objects.Pod, node_name: str):
            """((scope_ns, k, v), (topo_key, topo_val)) pairs for the
            pod's index-representable terms — ONE classification shared by
            add and remove so the refcounts always balance; terms it skips
            are exactly the ones the caller routes to sym_complex."""
            other = ssn.nodes.get(node_name)
            for term in pod.spec.affinity.pod_anti_affinity.required_terms:
                sel = term.label_selector
                if other is not None and sel is not None \
                        and not sel.match_expressions \
                        and len(sel.match_labels) == 1:
                    ((k, v),) = sel.match_labels.items()
                    topo = (term.topology_key,
                            _node_topology_value(other, term.topology_key))
                    for scope_ns in (term.namespaces
                                     or [pod.metadata.namespace]):
                        yield (scope_ns, k, v), topo
                else:
                    yield None, term

        def _anti_add(uid: str, pod: objects.Pod, node_name: str) -> None:
            if uid in anti_resident:
                return  # idempotent (unevict re-fires allocate)
            anti_resident[uid] = (pod, node_name)
            for key, payload in _sym_single_entries(pod, node_name):
                if key is not None:
                    counts = sym_single.setdefault(key, {})
                    counts[payload] = counts.get(payload, 0) + 1
                else:
                    sym_complex.setdefault(uid, []).append(
                        (payload, pod.metadata.namespace, node_name))

        def _anti_remove(uid: str) -> Optional[tuple]:
            entry = anti_resident.pop(uid, None)
            if entry is None:
                return None
            pod, node_name = entry
            for key, payload in _sym_single_entries(pod, node_name):
                if key is not None:
                    counts = sym_single.get(key)
                    if counts is not None:
                        n = counts.get(payload, 0) - 1
                        if n <= 0:
                            counts.pop(payload, None)
                        else:
                            counts[payload] = n
            sym_complex.pop(uid, None)
            return entry

        for _node in all_nodes:
            for _t in _node.tasks.values():
                if _has_required_anti_affinity(_t.pod):
                    _anti_add(_t.uid, _t.pod, _node.name)

        # generation counter for caches derived from anti_resident: bumped
        # on every mutation so per-pod symmetry sets recompute exactly when
        # the resident picture changes mid-pass (the rebuild itself is
        # cheap — the inverted sym_single index above absorbs the
        # O(residents) work incrementally)
        anti_gen = [0]

        # per-node resident label-pair index: (uids, counts[(ns,k,v)],
        # ns_counts[ns]) built lazily per node from its live task map and
        # maintained through the same session events — turns "does any
        # resident match this selector" from a per-pod scan into dict
        # lookups (exact for single-pair match_labels selectors; multi-pair
        # positives and match_expressions fall back to the pod scan).
        # Laziness also keeps the bulk-apply bypass safe: the bulk writeback
        # fires no events, but it runs before any serial predicate does, so
        # a node's index is always FIRST built from post-bulk live state
        # (same argument as anti_resident above; allocate's bulk solve runs
        # at most once per session)
        node_label_idx: Dict[str, tuple] = {}
        uid_node: Dict[str, str] = {}

        def _build_label_idx(node: NodeInfo) -> tuple:
            uids, counts, ns_counts = set(), {}, {}
            for t in node.tasks.values():
                pod = t.pod
                if pod is None:
                    continue
                uids.add(t.uid)
                ns = pod.metadata.namespace
                ns_counts[ns] = ns_counts.get(ns, 0) + 1
                uid_node[t.uid] = node.name
                for k, v in pod.metadata.labels.items():
                    key = (ns, k, v)
                    counts[key] = counts.get(key, 0) + 1
            idx = (uids, counts, ns_counts)
            node_label_idx[node.name] = idx
            return idx

        def _label_idx_add(t) -> None:
            uid_node[t.uid] = t.node_name
            idx = node_label_idx.get(t.node_name)
            if idx is None:
                return
            uids, counts, ns_counts = idx
            if t.uid in uids:
                return  # idempotent (unevict re-fires allocate)
            uids.add(t.uid)
            ns = t.pod.metadata.namespace
            ns_counts[ns] = ns_counts.get(ns, 0) + 1
            for k, v in t.pod.metadata.labels.items():
                key = (ns, k, v)
                counts[key] = counts.get(key, 0) + 1

        def _label_idx_remove(t) -> None:
            # unpipeline clears node_name before the event; the uid map
            # remembers where the pod was
            name = uid_node.pop(t.uid, None) or t.node_name
            idx = node_label_idx.get(name) if name else None
            if idx is None:
                return
            uids, counts, ns_counts = idx
            if t.uid not in uids:
                return
            uids.discard(t.uid)
            ns = t.pod.metadata.namespace
            ns_counts[ns] = ns_counts.get(ns, 0) - 1
            for k, v in t.pod.metadata.labels.items():
                key = (ns, k, v)
                counts[key] = counts.get(key, 0) - 1

        def _node_has_match(term, incoming_ns: str, node: NodeInfo):
            """Exact True/False from the index, or None when the pod scan
            must decide (see _affinity_term_satisfied)."""
            sel = term.label_selector
            if sel is None:
                return False  # _selector_matches_pod is False for all pods
            if sel.match_expressions:
                return None
            idx = node_label_idx.get(node.name)
            if idx is None:
                idx = _build_label_idx(node)
            _, counts, ns_counts = idx
            namespaces = term.namespaces or [incoming_ns]
            pairs = sel.match_labels.items()
            if not pairs:
                # empty selector matches every pod in the namespace scope
                return any(ns_counts.get(ns, 0) > 0 for ns in namespaces)
            maybe = False
            for ns in namespaces:
                if all(counts.get((ns, k, v), 0) > 0 for k, v in pairs):
                    if len(pairs) == 1:
                        return True
                    maybe = True
            return None if maybe else False

        def _track_allocate(event) -> None:
            t = event.task
            if t.pod is not None and t.node_name:
                _label_idx_add(t)
            if _has_required_anti_affinity(t.pod) and t.node_name:
                _anti_add(t.uid, t.pod, t.node_name)
                anti_gen[0] += 1

        def _track_deallocate(event) -> None:
            t = event.task
            if t.pod is not None and t.status != TaskStatus.RELEASING:
                _label_idx_remove(t)
            if _has_required_anti_affinity(t.pod) and t.status != TaskStatus.RELEASING:
                if _anti_remove(t.uid) is not None:
                    anti_gen[0] += 1

        ssn.add_event_handler(EventHandler(
            _track_allocate, _track_deallocate,
            # the deallocate arm guards BOTH branches on status != RELEASING
            # — the tag lets the native engine skip it for evictions
            origin=(PLUGIN_NAME, self)))

        # session-scoped topology-domain index (node labels are fixed for
        # the session): key -> {value: [nodes]}, built lazily per key
        topo_domains: Dict[str, Dict[str, List[NodeInfo]]] = {}

        def _domains(key: str) -> Dict[str, List[NodeInfo]]:
            m = topo_domains.get(key)
            if m is None:
                m = topo_domains[key] = {}
                for nd in all_nodes:
                    m.setdefault(_node_topology_value(nd, key), []).append(nd)
            return m

        # per-incoming-pod symmetry exclusion domains, cached on the
        # anti_resident generation: one O(residents) scan per (pod,
        # generation) instead of per (pod, node) — the candidate sweep then
        # pays a set-membership check per node
        sym_cache: Dict[str, tuple] = {}

        def _sym_excluded(pod: objects.Pod):
            key = pod.metadata.uid or f"{pod.metadata.namespace}/{pod.metadata.name}"
            hit = sym_cache.get(key)
            if hit is not None and hit[0] == anti_gen[0]:
                return hit[1]
            # single-kv terms via the inverted index: O(pod labels) lookups
            excluded = set()
            ns = pod.metadata.namespace
            for k, v in pod.metadata.labels.items():
                counts = sym_single.get((ns, k, v))
                if counts:
                    excluded.update(counts)
            # the few complex-selector residents keep the per-pod scan
            for entries in sym_complex.values():
                for term, existing_ns, node_name in entries:
                    if _selector_matches_pod(term, pod, existing_ns):
                        other = ssn.nodes.get(node_name)
                        if other is not None:
                            excluded.add((
                                term.topology_key,
                                _node_topology_value(
                                    other, term.topology_key)))
            if len(sym_cache) > 8192:
                sym_cache.clear()
            sym_cache[key] = (anti_gen[0], excluded)
            return excluded

        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            pod = task.pod
            if pod is None:
                return

            # pod count (predicates.go:165)
            if node.allocatable.max_task_num <= len(node.tasks):
                raise FitFailure(NODE_POD_NUMBER_EXCEEDED)

            # node conditions (CheckNodeConditionPredicate)
            if not _node_condition(node, "Ready"):
                raise FitFailure("node(s) were not ready")
            if _node_condition(node, "NetworkUnavailable"):
                raise FitFailure("node(s) had network unavailable")

            # unschedulable spec (CheckNodeUnschedulablePredicate)
            if node.node is not None and node.node.spec.unschedulable:
                raise FitFailure("node(s) were unschedulable")

            # node selector + required node affinity
            if not pod_matches_node_selector(pod, node):
                raise FitFailure("node(s) didn't match node selector")

            # host ports
            if not host_ports_free(pod, node):
                raise FitFailure("node(s) didn't have free ports for the requested pod ports")

            # taints
            if not tolerates_taints(pod, node):
                raise FitFailure("node(s) had taints that the pod didn't tolerate")

            if memory_pressure and _node_condition(node, "MemoryPressure"):
                raise FitFailure("node(s) had memory pressure")
            if disk_pressure and _node_condition(node, "DiskPressure"):
                raise FitFailure("node(s) had disk pressure")
            if pid_pressure and _node_condition(node, "PIDPressure"):
                raise FitFailure("node(s) had pid pressure")

            # pod (anti-)affinity incl. required-term symmetry
            if (pod.spec.affinity is not None or anti_resident) and \
                    not pod_affinity_fits(pod, node, all_nodes,
                                          anti_resident, ssn.nodes,
                                          domains=_domains,
                                          sym_excluded=_sym_excluded(pod),
                                          node_has_match=_node_has_match):
                raise FitFailure("node(s) didn't match pod affinity/anti-affinity")

        ssn.add_predicate_fn(PLUGIN_NAME, predicate_fn)

        # residual surface for the allocate assist (ops/preemptview.py
        # alloc_best_node): exactly the chain links the dense base mask
        # cannot precompute — host ports and pod (anti-)affinity incl.
        # required-term symmetry — evaluated live with the same indexes
        # predicate_fn uses, so verdict conjunction is identical
        def residual_check(task: TaskInfo, node: NodeInfo) -> None:
            pod = task.pod
            if pod is None:
                return
            if not host_ports_free(pod, node):
                raise FitFailure(
                    "node(s) didn't have free ports for the requested pod ports")
            if (pod.spec.affinity is not None or anti_resident) and \
                    not pod_affinity_fits(pod, node, all_nodes,
                                          anti_resident, ssn.nodes,
                                          domains=_domains,
                                          sym_excluded=_sym_excluded(pod),
                                          node_has_match=_node_has_match):
                raise FitFailure(
                    "node(s) didn't match pod affinity/anti-affinity")

        def note_resident(task: TaskInfo) -> None:
            """Bulk-apply hook: a device-placed pod with required
            anti-affinity became resident without session events firing
            (ops/solver._apply_bulk exclusion groups)."""
            if t_pod := task.pod:
                _label_idx_add(task)
                if _has_required_anti_affinity(t_pod) and task.node_name:
                    _anti_add(task.uid, t_pod, task.node_name)
                    anti_gen[0] += 1

        self.note_resident = note_resident
        self.residual_check = residual_check
        self.needs_residual = lambda pod: (
            bool(anti_resident)
            or (pod is not None and (
                pod.spec.affinity is not None
                and (pod.spec.affinity.pod_affinity is not None
                     or pod.spec.affinity.pod_anti_affinity is not None)
                or any(p.host_port > 0 for c in pod.spec.containers
                       for p in c.ports))))


def new(arguments):
    return PredicatesPlugin(arguments)
