"""gang — the gang-scheduling policy (volcano pkg/scheduler/plugins/gang/gang.go).

Extension points: JobValid (enough valid tasks vs MinAvailable), Preemptable/
Reclaimable (victim's job must stay >= MinAvailable), JobOrder (non-ready
first), JobReady/JobPipelined; OnSessionClose writes Unschedulable conditions
and metrics.
"""

from __future__ import annotations

import time
from typing import List

from volcano_tpu.api import objects
from volcano_tpu.utils import clock
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.types import TaskStatus, ValidateResult
from volcano_tpu.api.unschedule_info import FitErrors
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "gang"


class GangPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(job: JobInfo):
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    pass_=False,
                    reason=objects.NOT_ENOUGH_PODS_REASON,
                    message=(
                        f"Not enough valid tasks for gang-scheduling, "
                        f"valid: {vtn}, min: {job.min_available}"
                    ),
                )
            return None

        # result depends only on the job's status index (valid_task_num)
        # and its static min_available — the session may memoize it per
        # (job, _status_version); see Session.job_valid
        valid_job_fn._status_version_keyed = True
        ssn.add_job_valid_fn(PLUGIN_NAME, valid_job_fn)

        def preemptable_fn(preemptor: TaskInfo, preemptees: List[TaskInfo]) -> List[TaskInfo]:
            victims = []
            # per-job occupancy map, DECREMENTED per nominated victim
            # (gang.go:82-86): one call may nominate at most
            # (ready - minAvailable) victims per gang — a static read
            # would let a single reclaim pass shred a gang below its min,
            # the partial-gang bug the sim auditor catches mechanically
            occupied_map = {}
            for preemptee in preemptees:
                job = ssn.jobs.get(preemptee.job)
                if job is None:
                    continue
                occupied = occupied_map.get(job.uid)
                if occupied is None:
                    occupied = job.ready_task_num()
                if job.min_available <= occupied - 1 or job.min_available == 1:
                    victims.append(preemptee)
                    occupied -= 1
                occupied_map[job.uid] = occupied
            return victims

        ssn.add_reclaimable_fn(PLUGIN_NAME, preemptable_fn)
        ssn.add_preemptable_fn(PLUGIN_NAME, preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1  # non-ready jobs first
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(PLUGIN_NAME, job_order_fn)
        ssn.add_job_ready_fn(PLUGIN_NAME, lambda job: job.ready())
        ssn.add_job_pipelined_fn(PLUGIN_NAME, lambda job: job.pipelined())

    def on_session_close(self, ssn) -> None:
        """Write fit errors + Unschedulable conditions for non-ready gangs
        (gang.go:137-180)."""
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if job.ready():
                continue
            unready = job.min_available - job.ready_task_num()
            msg = (
                f"{unready}/{len(job.tasks)} tasks in gang unschedulable: "
                f"{job.fit_error()}"
            )
            job.job_fit_errors = msg
            unschedulable_jobs += 1
            metrics.update_unschedule_task_count(job.name, unready)
            metrics.register_job_retry(job.name)

            jc = objects.PodGroupCondition(
                type=objects.POD_GROUP_UNSCHEDULABLE_TYPE,
                status="True",
                last_transition_time=clock.now(),
                transition_id=ssn.uid,
                reason=objects.NOT_ENOUGH_RESOURCES_REASON,
                message=msg,
            )
            try:
                ssn.update_job_condition(job, jc)
            except (KeyError, AttributeError):
                pass

            for task in job.task_status_index.get(TaskStatus.ALLOCATED, {}).values():
                if task.uid in job.nodes_fit_errors:
                    continue
                fe = FitErrors()
                fe.set_error(msg)
                job.nodes_fit_errors[task.uid] = fe

        metrics.update_unschedule_job_count(unschedulable_jobs)


def new(arguments):
    return GangPlugin(arguments)
