"""proportion — weighted fair-share ("water-filling") of cluster capacity
across queues (volcano pkg/scheduler/plugins/proportion/proportion.go).

Deserved shares are computed by iterating `deserved += remaining*w/Σw`,
clamping at each queue's request, until remaining is empty
(proportion.go:104-157). Provides QueueOrder (by share), Reclaimable
(victims only while their queue stays above deserved), Overused, and
JobEnqueueable (queue capability cap).
"""

from __future__ import annotations

from typing import Dict, List

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.share_helpers import min_resource, share as share_fn

from volcano_tpu.scheduler.framework.event_handlers import EventHandler
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "proportion"


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "share", "deserved", "allocated", "request")

    def __init__(self, queue_id: str, name: str, weight: int):
        self.queue_id = queue_id
        self.name = name
        self.weight = weight
        self.share = 0.0
        self.deserved = Resource.empty()
        self.allocated = Resource.empty()
        self.request = Resource.empty()


class ProportionPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total_resource = Resource.empty()
        self.queue_opts: Dict[str, _QueueAttr] = {}

    def name(self) -> str:
        return PLUGIN_NAME

    def _update_share(self, attr: _QueueAttr) -> None:
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share_fn(attr.allocated.get(rn), attr.deserved.get(rn))
            if s > res:
                res = s
        attr.share = res

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.cache.nodeaxis import add_total_allocatable

        add_total_allocatable(ssn, self.total_resource)

        # queue attributes from jobs (proportion.go:72-102): the per-task
        # walk collapses to the incrementally-maintained job sums —
        # allocated-status requests (job.allocated) and PENDING requests
        # (job.pending_sum), two O(1) adds per job
        for job in ssn.jobs.values():
            if job.queue not in self.queue_opts:
                queue = ssn.queues[job.queue]
                self.queue_opts[job.queue] = _QueueAttr(queue.uid, queue.name, queue.weight)
            attr = self.queue_opts[job.queue]
            attr.allocated.add(job.allocated)
            attr.request.add(job.allocated)
            attr.request.add(job.pending_sum)

        # iterative water-filling of deserved (proportion.go:104-157)
        remaining = self.total_resource.clone()
        meet: set[str] = set()
        while True:
            total_weight = sum(
                attr.weight for attr in self.queue_opts.values()
                if attr.queue_id not in meet
            )
            if total_weight == 0:
                break

            increased_total = Resource.empty()
            decreased_total = Resource.empty()
            for attr in self.queue_opts.values():
                if attr.queue_id in meet:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / total_weight)
                )
                if attr.request.less(attr.deserved):
                    attr.deserved = min_resource(attr.deserved, attr.request)
                    meet.add(attr.queue_id)
                self._update_share(attr)
                increased, decreased = attr.deserved.diff(old_deserved)
                increased_total.add(increased)
                decreased_total.add(decreased)

            remaining.sub(increased_total).add(decreased_total)
            if remaining.is_empty():
                break

        def queue_order_fn(l, r) -> int:
            ls = self.queue_opts[l.uid].share
            rs = self.queue_opts[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(PLUGIN_NAME, queue_order_fn)

        def reclaimable_fn(reclaimer, reclaimees: List) -> List:
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs.get(reclaimee.job)
                if job is None:
                    continue
                attr = self.queue_opts[job.queue]
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                # victim only while the queue stays >= deserved
                if attr.deserved.less_equal(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(PLUGIN_NAME, reclaimable_fn)

        def overused_fn(queue) -> bool:
            attr = self.queue_opts.get(queue.uid)
            if attr is None:
                return False
            return not attr.allocated.less_equal(attr.deserved)

        ssn.add_overused_fn(PLUGIN_NAME, overused_fn)

        def job_enqueueable_fn(job) -> bool:
            queue = ssn.queues[job.queue]
            capability = queue.queue.spec.capability
            if not capability:
                return True
            attr = self.queue_opts[job.queue]
            pg_resource = Resource.from_resource_list(job.pod_group.spec.min_resources)
            return pg_resource.clone().add(attr.allocated).less_equal(
                Resource.from_resource_list(capability)
            )

        ssn.add_job_enqueueable_fn(PLUGIN_NAME, job_enqueueable_fn)

        def on_allocate(event) -> None:
            job = ssn.jobs[event.task.job]
            attr = self.queue_opts[job.queue]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event) -> None:
            job = ssn.jobs[event.task.job]
            attr = self.queue_opts[job.queue]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate, deallocate_func=on_deallocate,
                         origin=(PLUGIN_NAME, self))
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.queue_opts = {}


def new(arguments):
    return ProportionPlugin(arguments)
