"""nodeorder — node scoring priorities
(volcano pkg/scheduler/plugins/nodeorder/nodeorder.go).

NodeOrderFn = LeastRequested + BalancedResourceAllocation + NodeAffinity,
each x its configurable weight (raw map scores, no normalize — matching the
reference, which calls only the k8s Map fns, nodeorder.go:161-200).
BatchNodeOrderFn = InterPodAffinity, normalized 0..10 across the node set
then x podaffinity.weight (nodeorder.go:202-220).

Implemented natively over the session's NodeInfo; the k8s formulas
(1.13-era priorities) are reproduced including the non-zero request
defaults (100 mCPU / 200 MB).
"""

from __future__ import annotations

import math
from typing import Dict, List

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.resource import Resource
from volcano_tpu.scheduler.framework.interface import Plugin
from volcano_tpu.scheduler.plugins.predicates import (
    _node_topology_value,
    _pods_on_node,
    _selector_matches_pod,
)

PLUGIN_NAME = "nodeorder"

NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"

MAX_PRIORITY = 10

# k8s non-zero request defaults (priorities/util)
DEFAULT_MILLI_CPU_REQUEST = 100.0
DEFAULT_MEMORY_REQUEST = 200.0 * 1024 * 1024


def _non_zero_request(res: Resource) -> tuple[float, float]:
    cpu = res.milli_cpu if res.milli_cpu != 0 else DEFAULT_MILLI_CPU_REQUEST
    mem = res.memory if res.memory != 0 else DEFAULT_MEMORY_REQUEST
    return cpu, mem


def least_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    """((capacity-requested)*10/capacity averaged over cpu+mem)."""
    req_cpu, req_mem = _non_zero_request(task.resreq)
    used_cpu, used_mem = node.used.milli_cpu, node.used.memory
    total_cpu = node.allocatable.milli_cpu
    total_mem = node.allocatable.memory

    def dim_score(capacity: float, requested: float) -> float:
        if capacity == 0 or requested > capacity:
            return 0.0
        return (capacity - requested) * float(MAX_PRIORITY) / capacity

    cpu_score = dim_score(total_cpu, used_cpu + req_cpu)
    mem_score = dim_score(total_mem, used_mem + req_mem)
    return math.floor((cpu_score + mem_score) / 2)


def balanced_resource_score(task: TaskInfo, node: NodeInfo) -> float:
    """10 - |cpuFraction - memFraction|*10; 0 when over capacity."""
    req_cpu, req_mem = _non_zero_request(task.resreq)
    total_cpu = node.allocatable.milli_cpu
    total_mem = node.allocatable.memory
    if total_cpu == 0 or total_mem == 0:
        return 0.0
    cpu_fraction = (node.used.milli_cpu + req_cpu) / total_cpu
    mem_fraction = (node.used.memory + req_mem) / total_mem
    if cpu_fraction >= 1 or mem_fraction >= 1:
        return 0.0
    return math.floor(MAX_PRIORITY - abs(cpu_fraction - mem_fraction) * MAX_PRIORITY)


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    """Sum of weights of matching preferred node-affinity terms (raw, like
    CalculateNodeAffinityPriorityMap without the normalize reduce)."""
    pod = task.pod
    if pod is None or pod.spec.affinity is None or pod.spec.affinity.node_affinity is None:
        return 0.0
    labels = node.node.metadata.labels if node.node is not None else {}
    score = 0
    for pref in pod.spec.affinity.node_affinity.preferred_terms:
        if pref.weight != 0 and pref.preference.matches(labels):
            score += pref.weight
    return float(score)


def inter_pod_affinity_scores(
    task: TaskInfo, nodes: List[NodeInfo], hard_pod_affinity_weight: int = 1
) -> Dict[str, float]:
    """k8s InterPodAffinityPriority: accumulate signed term weights per
    topology domain (incoming pod's preferred terms against existing pods,
    existing pods' preferred terms against the incoming pod, and the
    hard-affinity symmetric weight), then normalize to 0..MAX_PRIORITY."""
    pod = task.pod
    if pod is None:
        return {}
    counts: Dict[str, float] = {n.name: 0.0 for n in nodes}

    def add_topo(term: objects.PodAffinityTerm, anchor: NodeInfo, weight: float) -> None:
        topo = _node_topology_value(anchor, term.topology_key)
        for n in nodes:
            if _node_topology_value(n, term.topology_key) == topo:
                counts[n.name] += weight

    my_affinity = pod.spec.affinity
    for node in nodes:
        for existing in _pods_on_node(node):
            # incoming pod's preferred (anti-)affinity vs existing pod
            if my_affinity is not None:
                if my_affinity.pod_affinity is not None:
                    for wt in my_affinity.pod_affinity.preferred_terms:
                        if _selector_matches_pod(wt.pod_affinity_term, existing, pod.metadata.namespace):
                            add_topo(wt.pod_affinity_term, node, float(wt.weight))
                if my_affinity.pod_anti_affinity is not None:
                    for wt in my_affinity.pod_anti_affinity.preferred_terms:
                        if _selector_matches_pod(wt.pod_affinity_term, existing, pod.metadata.namespace):
                            add_topo(wt.pod_affinity_term, node, -float(wt.weight))
            # existing pod's (anti-)affinity vs incoming pod
            ea = existing.spec.affinity
            if ea is not None:
                if ea.pod_affinity is not None:
                    for wt in ea.pod_affinity.preferred_terms:
                        if _selector_matches_pod(wt.pod_affinity_term, pod, existing.metadata.namespace):
                            add_topo(wt.pod_affinity_term, node, float(wt.weight))
                    # hard-affinity symmetry
                    for term in ea.pod_affinity.required_terms:
                        if _selector_matches_pod(term, pod, existing.metadata.namespace):
                            add_topo(term, node, float(hard_pod_affinity_weight))
                if ea.pod_anti_affinity is not None:
                    for wt in ea.pod_anti_affinity.preferred_terms:
                        if _selector_matches_pod(wt.pod_affinity_term, pod, existing.metadata.namespace):
                            add_topo(wt.pod_affinity_term, node, -float(wt.weight))

    values = list(counts.values())
    max_c, min_c = max(values, default=0.0), min(values, default=0.0)
    if max_c == min_c:
        return {name: 0.0 for name in counts}
    return {
        name: float(MAX_PRIORITY) * (c - min_c) / (max_c - min_c)
        for name, c in counts.items()
    }


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        from volcano_tpu.scheduler.framework.arguments import Arguments

        args = self.arguments if isinstance(self.arguments, Arguments) else Arguments(self.arguments)
        least_req_weight = args.get_int(LEAST_REQUESTED_WEIGHT, 1)
        node_affinity_weight = args.get_int(NODE_AFFINITY_WEIGHT, 1)
        pod_affinity_weight = args.get_int(POD_AFFINITY_WEIGHT, 1)
        balanced_weight = args.get_int(BALANCED_RESOURCE_WEIGHT, 1)

        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            score = 0.0
            score += least_requested_score(task, node) * least_req_weight
            score += balanced_resource_score(task, node) * balanced_weight
            score += node_affinity_score(task, node) * node_affinity_weight
            return score

        ssn.add_node_order_fn(PLUGIN_NAME, node_order_fn)

        def batch_node_order_fn(task: TaskInfo, nodes: List[NodeInfo]) -> Dict[str, float]:
            scores = inter_pod_affinity_scores(task, nodes)
            return {name: s * pod_affinity_weight for name, s in scores.items()}

        ssn.add_batch_node_order_fn(PLUGIN_NAME, batch_node_order_fn)


def new(arguments):
    return NodeOrderPlugin(arguments)
