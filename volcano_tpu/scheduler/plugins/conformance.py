"""conformance — never evict cluster-critical workloads
(volcano pkg/scheduler/plugins/conformance/conformance.go:44-66)."""

from __future__ import annotations

from typing import List

from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework.interface import Plugin

PLUGIN_NAME = "conformance"

KUBE_SYSTEM_NAMESPACE = "kube-system"


class ConformancePlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor, evictees: List) -> List:
            victims = []
            for evictee in evictees:
                class_name = (
                    evictee.pod.spec.priority_class_name if evictee.pod else ""
                )
                if class_name in (
                    objects.SYSTEM_CLUSTER_CRITICAL,
                    objects.SYSTEM_NODE_CRITICAL,
                ) or evictee.namespace == KUBE_SYSTEM_NAMESPACE:
                    continue
                victims.append(evictee)
            return victims

        ssn.add_preemptable_fn(PLUGIN_NAME, evictable_fn)
        ssn.add_reclaimable_fn(PLUGIN_NAME, evictable_fn)


def new(arguments):
    return ConformancePlugin(arguments)
