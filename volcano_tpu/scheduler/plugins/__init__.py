"""Policy plugins (volcano pkg/scheduler/plugins)."""

from volcano_tpu.scheduler.plugins import factory  # noqa: F401  (registers all)
from volcano_tpu.scheduler.plugins.defaults import apply_plugin_conf_defaults
