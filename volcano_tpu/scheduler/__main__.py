"""vc-scheduler binary equivalent: ``python -m volcano_tpu.scheduler``.

Maps the reference's flag surface (cmd/scheduler/app/options/options.go:78-108
+ server.go:76-160) onto the in-process substrate:

- ``--scheduler-name/--scheduler-conf/--schedule-period/--default-queue`` as
  in the reference;
- ``--leader-elect`` runs the loop behind a store resource-lock election
  (server.go:131-160); only the leader schedules;
- ``--listen-address`` serves /metrics, ``--healthz-address`` serves
  /healthz (server.go:97-100; apis/helpers.go:164);
- node-sampling knobs land in options.ServerOpts exactly where
  scheduler_helper reads them (scheduler_helper.go:43);
- ``--cluster-state`` seeds the store from a YAML corpus (nodes/queues/jobs)
  so a standalone run has something to schedule; without an external API
  server the full cluster (controllers + kubelet sim) runs in-process.

``--run-for N`` exits after N seconds (the e2e/smoke hook); default runs
until SIGINT.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

import yaml

from volcano_tpu import version
from volcano_tpu.scheduler import options


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(prog="vc-scheduler")
    d = options.ServerOpts()
    ap.add_argument("--scheduler-name", default=d.scheduler_name,
                    help="only pods with this schedulerName are scheduled")
    ap.add_argument("--scheduler-conf", default="",
                    help="policy YAML path, hot-reloaded every cycle")
    ap.add_argument("--schedule-period", type=float,
                    default=d.schedule_period_seconds, metavar="SECONDS")
    ap.add_argument("--default-queue", default=d.default_queue)
    ap.add_argument("--express", action="store_true", default=False,
                    help="enable the event-driven express lane: eligible "
                         "interactive arrivals place between periodic "
                         "sessions (volcano_tpu/express)")
    ap.add_argument("--pipeline", action="store_true", default=False,
                    help="enable the continuous scheduling pipeline: "
                         "double-buffered sessions with speculative "
                         "solve-ahead (volcano_tpu/pipeline); "
                         "VOLCANO_TPU_PIPELINE=0 forces the serial loop")
    ap.add_argument("--leader-elect", action="store_true", default=False)
    ap.add_argument("--lock-object-namespace", default="volcano-system")
    ap.add_argument("--leader-elect-identity", default="",
                    help="holder identity (default: host-pid)")
    ap.add_argument("--listen-address", default=d.listen_address,
                    help="metrics address (reference :8080)")
    ap.add_argument("--healthz-address", default=d.healthz_address)
    ap.add_argument("--minimum-feasible-nodes", type=int,
                    default=d.min_nodes_to_find)
    ap.add_argument("--minimum-percentage-of-nodes-to-find", type=int,
                    default=d.min_percentage_of_nodes_to_find)
    ap.add_argument("--percentage-of-nodes-to-find", type=int,
                    default=d.percentage_of_nodes_to_find)
    ap.add_argument("--cluster-state", default="",
                    help="YAML corpus seeding nodes/queues/jobs (example/)")
    ap.add_argument("--api-address", default="",
                    help="serve the store API gateway (vcctl --server "
                         "target) on this address; ':0' picks a free port")
    ap.add_argument("--api-token", default="",
                    help="require 'Authorization: Bearer <token>' on every "
                         "gateway request (mandatory for non-loopback "
                         "--api-address)")
    ap.add_argument("--api-tls-cert", default="",
                    help="serve the gateway over HTTPS with this cert chain")
    ap.add_argument("--api-tls-key", default="",
                    help="private key for --api-tls-cert")
    ap.add_argument("--api-server-only", action="store_true",
                    help="run store + admission + controllers + kubelet + "
                         "gateway WITHOUT the in-process scheduler: an "
                         "out-of-process scheduler consumes this process "
                         "over RemoteStore watches (use with "
                         "--api-address)")
    ap.add_argument("--server", default="",
                    help="remote-scheduler mode: run ONLY the scheduler "
                         "stack against an --api-server-only cluster "
                         "process at host:port — informers over HTTP "
                         "long-poll, binds/statuses written back through "
                         "the gateway (the vc-scheduler-vs-API-server "
                         "process split)")
    ap.add_argument("--token", default="",
                    help="bearer token for a --server gateway started "
                         "with --api-token")
    ap.add_argument("--insecure-skip-tls-verify", action="store_true",
                    help="accept self-signed gateway certificates "
                         "(https --server)")
    ap.add_argument("--run-for", type=float, default=0.0,
                    help="exit after N seconds (0 = until SIGINT)")
    ap.add_argument("--version", action="store_true")
    ap.add_argument("-v", "--verbosity", type=int, default=0)
    return ap.parse_args(argv)


def seed_cluster_state(store, path: str) -> None:
    """Load a multi-document YAML corpus into the store: Node/Queue docs go
    in directly; Job docs go through the CLI loader (admission applies)."""
    from volcano_tpu.api import objects
    from volcano_tpu.cli import job as job_cli

    with open(path) as f:
        docs = [d for d in yaml.safe_load_all(f.read()) if d]
    for doc in docs:
        kind = doc.get("kind", "")
        meta = doc.get("metadata", {}) or {}
        if kind == "Node":
            cap = (doc.get("status", {}) or {}).get("capacity", {}) or {}
            capacity = {
                "cpu": str(cap.get("cpu", "8")),
                "memory": str(cap.get("memory", "16Gi")),
                "pods": str(cap.get("pods", "110")),
            }
            node = objects.Node(
                metadata=objects.ObjectMeta(
                    name=meta.get("name", "node"),
                    labels=dict(meta.get("labels") or {})),
                status=objects.NodeStatus(
                    capacity=dict(capacity), allocatable=dict(capacity),
                    conditions=[objects.NodeCondition(
                        type="Ready", status="True")]))
            if store.try_get("Node", "", node.metadata.name) is None:
                store.create(node)
        elif kind == "Queue":
            spec = doc.get("spec", {}) or {}
            q = objects.Queue(
                metadata=objects.ObjectMeta(name=meta.get("name", "default")),
                spec=objects.QueueSpec(weight=int(spec.get("weight", 1))))
            if store.try_get("Queue", "", q.metadata.name) is None:
                store.create(q)
        elif kind == "Job":
            name = meta.get("name", "")
            ns = meta.get("namespace", "default")
            if name and store.try_get("Job", ns, name) is not None:
                continue  # re-seed (restart / HA standby): already present
            job_cli.run_job(store, yaml.safe_dump(doc))


def _make_elector(args, store, run_workload, stop_workload, fence=None):
    """Leader-elect wiring shared by the in-process and remote modes:
    identity derivation, the store-backed ConfigMap lock, and the elector
    whose callbacks start/stop the mode's workload. ``fence`` (called
    with the acquired epoch BEFORE the workload starts) stamps the
    fencing token onto the effector write-path, so no session of the new
    term ever writes unfenced (store/store.py FencedError)."""
    import os
    import socket

    from volcano_tpu.scheduler.leaderelection import (
        LeaderElector, ResourceLock)

    identity = (args.leader_elect_identity
                or f"{socket.gethostname()}-{os.getpid()}")
    lock = ResourceLock(
        store, args.lock_object_namespace, args.scheduler_name, identity)
    holder = {}

    def on_started():
        if fence is not None:
            fence(holder["elector"].epoch())
        run_workload()

    elector = LeaderElector(
        lock,
        on_started_leading=on_started,
        on_stopped_leading=stop_workload)
    holder["elector"] = elector
    elector.start()
    logging.info("leader election enabled (identity=%s)", identity)
    return elector


def _wait_for_signal_or_deadline(args, stop_evt) -> None:
    """Install SIGINT/SIGTERM -> stop_evt, wait (bounded by --run-for),
    restore handlers — the run-loop scaffold shared by the in-process and
    remote-scheduler modes."""

    def on_signal(signum, frame):
        stop_evt.set()

    prev_handlers = {}
    try:
        for sig in (signal.SIGINT, signal.SIGTERM):
            prev_handlers[sig] = signal.signal(sig, on_signal)
    except ValueError:
        pass  # not the main thread (tests drive main() directly)

    try:
        stop_evt.wait(timeout=args.run_for or None)
    finally:
        stop_evt.set()
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)


def run_remote_scheduler(args) -> int:
    """The scheduler as its own OS process against a remote API-server
    process (one run with --api-server-only --api-address): informer
    streams arrive over RemoteStore long-poll watches, effector writes
    (binds, conditions, statuses) return through the gateway, and leader
    election CASes the same remote ConfigMap lock — the reference's
    vc-scheduler binary shape (cmd/scheduler/app/server.go)."""

    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.httpserver import ObservabilityServer
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.store.remote import RemoteStore

    remote = RemoteStore(args.server, token=args.token or None,
                         tls_verify=not args.insecure_skip_tls_verify)
    if not remote.healthy():
        logging.error("gateway at %s is not reachable/healthy", args.server)
        return 1
    if args.cluster_state:
        # the seed corpus goes THROUGH the gateway (admission applies
        # server-side), so a seeded remote run schedules rather than
        # silently seeing an empty cluster
        seed_cluster_state(remote, args.cluster_state)
    cache = SchedulerCache(
        store=remote, scheduler_name=args.scheduler_name,
        default_queue=args.default_queue)
    cache.run()
    scheduler = Scheduler(
        cache, scheduler_conf="", schedule_period=args.schedule_period,
        express=args.express, pipeline=args.pipeline)
    if args.scheduler_conf:
        scheduler.conf_path = args.scheduler_conf

    stop_evt = threading.Event()
    ha_member = None
    metrics_srv = ObservabilityServer(args.listen_address).start()
    healthz_srv = ObservabilityServer(
        args.healthz_address,
        healthy=lambda: not stop_evt.is_set()
        and (ha_member is None or ha_member.healthy())
        and remote.healthy(timeout=2.0)).start()
    logging.info(
        "remote scheduler against %s; metrics on :%d/metrics, healthz on "
        ":%d/healthz", args.server, metrics_srv.port, healthz_srv.port)

    if args.leader_elect:
        # the full HA member shape (scheduler/ha.py): the lock ConfigMap
        # lives in the REMOTE store — competing scheduler processes CAS
        # the same record through the gateway, the gateway's store
        # advances its fence from the winning lease, and the loser's
        # in-flight writes are rejected server-side. While standby, the
        # cache keeps following the watch stream and the snapshot keeper
        # stays warm for a bounded takeover.
        from volcano_tpu.scheduler.ha import FailoverScheduler

        ha_member = FailoverScheduler(
            scheduler, remote,
            lock_namespace=args.lock_object_namespace,
            lock_name=args.scheduler_name,
            identity=args.leader_elect_identity).start()
    else:
        scheduler.run()

    _wait_for_signal_or_deadline(args, stop_evt)

    if ha_member is not None:
        ha_member.stop()
    else:
        scheduler.stop()
    remote.flush_events()
    remote.stop_watches()
    metrics_srv.stop()
    healthz_srv.stop()
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.version:
        sys.stdout.write(version.version_string())
        return 0
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 3 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")

    # flags land in the global ServerOpts read by scheduler_helper
    o = options.server_opts
    o.scheduler_name = args.scheduler_name
    o.scheduler_conf = args.scheduler_conf
    o.schedule_period_seconds = args.schedule_period
    o.default_queue = args.default_queue
    o.enable_leader_election = args.leader_elect
    o.min_nodes_to_find = args.minimum_feasible_nodes
    o.min_percentage_of_nodes_to_find = args.minimum_percentage_of_nodes_to_find
    o.percentage_of_nodes_to_find = args.percentage_of_nodes_to_find
    o.listen_address = args.listen_address
    o.healthz_address = args.healthz_address

    if args.server:
        return run_remote_scheduler(args)

    from volcano_tpu.cluster import Cluster
    from volcano_tpu.scheduler.httpserver import ObservabilityServer

    cluster = Cluster(
        scheduler_name=args.scheduler_name,
        default_queue=args.default_queue,
        schedule_period=args.schedule_period)
    if args.scheduler_conf:
        cluster.scheduler.conf_path = args.scheduler_conf
    if args.cluster_state:
        seed_cluster_state(cluster.store, args.cluster_state)

    stop_evt = threading.Event()
    elector = None
    metrics_srv = ObservabilityServer(args.listen_address).start()
    # healthz tracks elector liveness too: a dead elector thread means no
    # scheduler is running even though the process is up
    healthz_srv = ObservabilityServer(
        args.healthz_address,
        healthy=lambda: not stop_evt.is_set()
        and (elector is None or elector.healthy())).start()
    logging.info("metrics on :%d/metrics, healthz on :%d/healthz",
                 metrics_srv.port, healthz_srv.port)

    api_srv = None
    if args.api_address:
        from volcano_tpu.store.gateway import ApiGateway

        api_srv = ApiGateway(
            cluster.store, args.api_address,
            token=args.api_token or None,
            tls_cert=args.api_tls_cert or None,
            tls_key=args.api_tls_key or None).start()
        # the flush=True print is the port-discovery contract for tools
        # spawning this process with --api-address :0
        print(f"api gateway on :{api_srv.port}", flush=True)
        logging.info("api gateway on :%d (vcctl --server target)",
                     api_srv.port)

    if args.leader_elect:
        elector = _make_elector(
            args, cluster.store,
            lambda: cluster.run(scheduling=not args.api_server_only),
            cluster.stop,
            fence=cluster.cache.set_fence_epoch)
    else:
        cluster.run(scheduling=not args.api_server_only)

    _wait_for_signal_or_deadline(args, stop_evt)

    if elector is not None:
        elector.stop()
    else:
        cluster.stop()
    if api_srv is not None:
        api_srv.stop()
    metrics_srv.stop()
    healthz_srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
