"""Scheduler server options
(volcano cmd/scheduler/app/options/options.go:44-108)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ServerOpts:
    scheduler_name: str = "volcano"
    scheduler_conf: str = ""
    schedule_period_seconds: float = 1.0
    default_queue: str = "default"
    enable_leader_election: bool = True
    enable_priority_class: bool = True
    # node-sampling knobs (options.go:37-40); 0 percentage = adaptive
    min_nodes_to_find: int = 100
    min_percentage_of_nodes_to_find: int = 5
    percentage_of_nodes_to_find: int = 0
    listen_address: str = ":8080"
    healthz_address: str = "127.0.0.1:11251"


# Global singleton read by scheduler_helper (the reference does the same,
# scheduler_helper.go:43).
server_opts = ServerOpts()
