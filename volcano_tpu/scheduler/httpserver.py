"""Served observability endpoints: /metrics and /healthz.

The reference serves Prometheus metrics on ``:8080/metrics`` through the
default mux (cmd/scheduler/app/server.go:97-100) and a healthz probe on
``127.0.0.1:11251`` via apis/helpers.go:164 StartHealthz. Here one
ThreadingHTTPServer per address serves:

- ``/metrics``  — ``volcano_tpu.scheduler.metrics.render()`` (the 9 series
  with the reference's exact names, metrics.py);
- ``/healthz``  — 200 ``ok`` while the supplied ``healthy()`` callable holds
  (mirrors the max-frame-grace healthz check semantics: report unhealthy when
  the scheduler loop stops making progress).
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from volcano_tpu.scheduler import metrics

logger = logging.getLogger(__name__)


def _parse_address(address: str, default_host: str = "") -> Tuple[str, int]:
    """':8080' -> (default_host, 8080); '127.0.0.1:11251' -> pair."""
    host, _, port = address.rpartition(":")
    return host or default_host, int(port)


class ObservabilityServer:
    """Serves /metrics and /healthz on one address; port 0 picks a free
    port (exposed as ``.port`` after start)."""

    def __init__(self, address: str = ":0",
                 healthy: Optional[Callable[[], bool]] = None):
        self._address = _parse_address(address)
        self._healthy = healthy or (lambda: True)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    def start(self) -> "ObservabilityServer":
        healthy = self._healthy

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] == "/metrics":
                    body = metrics.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4")
                elif self.path.split("?", 1)[0] == "/healthz":
                    ok = False
                    try:
                        ok = bool(healthy())
                    except Exception:
                        logger.exception("healthz check failed")
                    body = b"ok" if ok else b"unhealthy"
                    self.send_response(200 if ok else 500)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                logger.debug("http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(self._address, Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
