"""Predicate/prioritize/select helpers for the serial (oracle) backend
(volcano pkg/scheduler/util/scheduler_helper.go).

The reference fans these loops out over 16 workers; here they are serial and
deterministic — this path is the *parity oracle* for the TPU backend
(volcano_tpu.ops), which replaces the whole (tasks x nodes) sweep with one
batched solve. Deliberate divergence from the reference: best-node ties are
broken by node name, not randomly (scheduler_helper.go:209), so Go-loop vs
TPU bindings can be compared byte-for-byte.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.unschedule_info import FitError, FitErrors, FitFailure
from volcano_tpu.scheduler.options import server_opts

BASELINE_PERCENTAGE_OF_NODES_TO_FIND = 50

# Round-robin start index so all nodes get examined across cycles
# (scheduler_helper.go:38 lastProcessedNodeIndex).
_last_processed_node_index = 0


def calculate_num_of_feasible_nodes_to_find(num_all_nodes: int) -> int:
    """Adaptive sampling: (50 - n/125)%%, floored at min-percentage and
    min-nodes (scheduler_helper.go:42-60)."""
    opts = server_opts
    if num_all_nodes <= opts.min_nodes_to_find or opts.percentage_of_nodes_to_find >= 100:
        return num_all_nodes

    adaptive = opts.percentage_of_nodes_to_find
    if adaptive <= 0:
        adaptive = BASELINE_PERCENTAGE_OF_NODES_TO_FIND - num_all_nodes // 125
        if adaptive < opts.min_percentage_of_nodes_to_find:
            adaptive = opts.min_percentage_of_nodes_to_find

    num_nodes = num_all_nodes * adaptive // 100
    return max(num_nodes, opts.min_nodes_to_find)


def predicate_nodes(
    task: TaskInfo, nodes: List[NodeInfo], fn: Callable
) -> Tuple[List[NodeInfo], FitErrors]:
    """Find up to the sampled number of feasible nodes, starting from where
    the previous cycle left off (scheduler_helper.go:64-118)."""
    global _last_processed_node_index

    fe = FitErrors()
    all_nodes = len(nodes)
    if all_nodes == 0:
        return [], fe
    num_to_find = calculate_num_of_feasible_nodes_to_find(all_nodes)

    found: List[NodeInfo] = []
    processed = 0
    for index in range(all_nodes):
        node = nodes[(_last_processed_node_index + index) % all_nodes]
        processed += 1
        try:
            fn(task, node)
        except FitFailure as err:
            fe.set_node_error(node.name, err.fit_error(task, node))
            continue
        found.append(node)
        if len(found) >= num_to_find:
            break

    _last_processed_node_index = (_last_processed_node_index + processed) % all_nodes
    return found, fe


def reset_round_robin() -> None:
    """Reset cross-cycle sampling state (for deterministic tests/benchmarks)."""
    global _last_processed_node_index
    _last_processed_node_index = 0


def prioritize_nodes(
    task: TaskInfo,
    nodes: List[NodeInfo],
    batch_fn: Callable,
    map_fn: Callable,
    reduce_fn: Callable,
) -> Dict[float, List[NodeInfo]]:
    """score -> nodes map (scheduler_helper.go:120-183)."""
    import math

    plugin_node_scores: Dict[str, Dict[str, float]] = {}
    node_order_scores: Dict[str, float] = {}
    for node in nodes:
        map_scores, order_score = map_fn(task, node)
        for plugin, score in map_scores.items():
            plugin_node_scores.setdefault(plugin, {})[node.name] = float(
                math.floor(score)
            )
        node_order_scores[node.name] = order_score

    reduce_scores = reduce_fn(task, plugin_node_scores)
    batch_scores = batch_fn(task, nodes)

    node_scores: Dict[float, List[NodeInfo]] = {}
    for node in nodes:
        score = reduce_scores.get(node.name, 0.0)
        score += node_order_scores.get(node.name, 0.0)
        score += batch_scores.get(node.name, 0.0)
        node_scores.setdefault(score, []).append(node)
    return node_scores


def sort_nodes(node_scores: Dict[float, List[NodeInfo]]) -> List[NodeInfo]:
    """Nodes in descending score order (scheduler_helper.go:185-197)."""
    out: List[NodeInfo] = []
    for score in sorted(node_scores, reverse=True):
        out.extend(node_scores[score])
    return out


def select_best_node(node_scores: Dict[float, List[NodeInfo]]) -> NodeInfo:
    """Highest-scoring node; deterministic name tie-break (the reference picks
    randomly, scheduler_helper.go:200-211 — divergence documented above)."""
    best_nodes: List[NodeInfo] = []
    max_score = -1.0
    for score, node_list in node_scores.items():
        if score > max_score:
            max_score = score
            best_nodes = node_list
    return min(best_nodes, key=lambda n: n.name)


def get_node_list(nodes: Dict[str, NodeInfo]) -> List[NodeInfo]:
    """Deterministic (name-sorted) node list; the reference's map iteration
    is randomized, ours is canonical for replay parity."""
    return [nodes[name] for name in sorted(nodes)]
