"""Synthetic-object builders and fake effectors for tests and benchmarks
(the analog of volcano pkg/scheduler/util/test_utils.go).

The fakes plug into the Binder/Evictor/StatusUpdater/VolumeBinder seam of the
scheduler cache (cache/interface.go:58-76) — the same seam the TPU parity
harness and the deterministic replay benchmarks use.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from volcano_tpu.api import objects


def build_resource_list(cpu: str, memory: str, **scalars) -> Dict[str, object]:
    """e.g. build_resource_list("2000m", "4Gi", **{"nvidia.com/gpu": "1"})"""
    rl: Dict[str, object] = {"cpu": cpu, "memory": memory}
    rl.update(scalars)
    return rl


def build_resource_list_with_pods(
    cpu: str, memory: str, pods: int = 110, **scalars
) -> Dict[str, object]:
    rl = build_resource_list(cpu, memory, **scalars)
    rl["pods"] = pods
    return rl


def build_node(
    name: str,
    allocatable: Dict[str, object],
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[Dict[str, object]] = None,
) -> objects.Node:
    node = objects.Node(
        metadata=objects.ObjectMeta(name=name, labels=dict(labels or {})),
        status=objects.NodeStatus(
            capacity=dict(capacity if capacity is not None else allocatable),
            allocatable=dict(allocatable),
        ),
    )
    node.metadata.ensure_identity()
    return node


def build_pod(
    namespace: str,
    name: str,
    node_name: str,
    phase: str,
    request: Dict[str, object],
    group_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    priority: Optional[int] = None,
) -> objects.Pod:
    annotations = {}
    if group_name:
        annotations[objects.GROUP_NAME_ANNOTATION_KEY] = group_name
    pod = objects.Pod(
        metadata=objects.ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"{namespace}-{name}",
            labels=dict(labels or {}),
            annotations=annotations,
        ),
        spec=objects.PodSpec(
            node_name=node_name,
            node_selector=dict(node_selector or {}),
            containers=[objects.Container(name="c", requests=dict(request))],
            priority=priority,
        ),
        status=objects.PodStatus(phase=phase),
    )
    pod.metadata.ensure_identity()
    return pod


def build_pod_group(
    name: str,
    namespace: str = "default",
    min_member: int = 1,
    queue: str = "default",
    phase: str = objects.PodGroupPhase.INQUEUE,
    min_resources: Optional[Dict[str, object]] = None,
) -> objects.PodGroup:
    pg = objects.PodGroup(
        metadata=objects.ObjectMeta(name=name, namespace=namespace),
        spec=objects.PodGroupSpec(
            min_member=min_member, queue=queue, min_resources=min_resources
        ),
        status=objects.PodGroupStatus(phase=phase),
    )
    pg.metadata.ensure_identity()
    return pg


def build_queue(name: str, weight: int = 1, capability=None) -> objects.Queue:
    q = objects.Queue(
        metadata=objects.ObjectMeta(name=name, namespace=""),
        spec=objects.QueueSpec(weight=weight, capability=capability),
    )
    q.metadata.ensure_identity()
    return q


class FakeBinder:
    """Records binds; signals each via a condition for completion waits
    (test_utils.go:98-120)."""

    def __init__(self):
        self.binds: Dict[str, str] = {}  # "ns/name" -> node
        self.channel: List[str] = []
        self._cond = threading.Condition()

    def bind(self, pod: objects.Pod, hostname: str) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._cond:
            self.binds[key] = hostname
            self.channel.append(key)
            self._cond.notify_all()

    def bind_many(self, pairs) -> None:
        """Batch bind under one lock acquisition (bulk-apply fast path)."""
        keyed = [
            (f"{pod.metadata.namespace}/{pod.metadata.name}", hostname)
            for pod, hostname in pairs
        ]
        with self._cond:
            self.binds.update(keyed)
            self.channel.extend(k for k, _ in keyed)
            self._cond.notify_all()

    # the keyed path needs no pod objects (the k8s Bind subresource binds
    # by name + target); the bulk writeback then skips per-task .pod
    # extraction entirely and passes pods=None
    KEYED_NEEDS_PODS = False

    def bind_many_keyed(self, keys, pods, hosts) -> None:
        """Batch bind with caller-derived ns/name keys (the bulk-apply
        writeback already built them); skips 50k metadata re-derivations.
        ``pods`` may be None (see KEYED_NEEDS_PODS)."""
        with self._cond:
            self.binds.update(zip(keys, hosts))
            self.channel.extend(keys)
            self._cond.notify_all()

    def wait_for_binds(self, n: int, timeout: float = 5.0) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: len(self.binds) >= n, timeout)


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []
        self._cond = threading.Condition()

    def evict(self, pod: objects.Pod, reason: str = "") -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._cond:
            self.evicts.append(key)
            self._cond.notify_all()

    def wait_for_evicts(self, n: int, timeout: float = 5.0) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: len(self.evicts) >= n, timeout)


class FakeStatusUpdater:
    """No-op status updater (test_utils.go:139-152)."""

    def update_pod_condition(self, pod, condition) -> None:
        pass

    def update_pod_group(self, pg, status=None) -> None:
        pass


class FakeVolumeBinder:
    """No-op volume binder (test_utils.go:154-165). IS_NOOP lets the bulk
    apply path skip 2 calls per placement."""

    IS_NOOP = True

    def allocate_volumes(self, task, hostname: str) -> None:
        pass

    def bind_volumes(self, task) -> None:
        pass
