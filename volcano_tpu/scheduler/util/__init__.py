"""Scheduler helpers: priority queue, parallel predicate/score helpers,
test object builders and fake effectors."""
