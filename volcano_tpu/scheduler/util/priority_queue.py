"""Heap-based priority queue over an arbitrary less-fn
(volcano pkg/scheduler/util/priority_queue.go)."""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Optional


class _Item:
    __slots__ = ("value", "less_fn", "seq")

    def __init__(self, value, less_fn, seq):
        self.value = value
        self.less_fn = less_fn
        self.seq = seq

    def __lt__(self, other: "_Item") -> bool:
        if self.less_fn is None:
            return self.seq < other.seq
        if self.less_fn(self.value, other.value):
            return True
        if self.less_fn(other.value, self.value):
            return False
        return self.seq < other.seq  # stable among equals


class _CmpItem:
    """Heap item over a 3-way comparator: one dispatch per comparison
    instead of the boolean protocol's two (the equality probe) — the
    job/queue order chains cost microseconds per call, and heap pops at
    preempt scale pay ~log(n) comparisons each."""

    __slots__ = ("value", "cmp_fn", "seq")

    def __init__(self, value, cmp_fn, seq):
        self.value = value
        self.cmp_fn = cmp_fn
        self.seq = seq

    def __lt__(self, other: "_CmpItem") -> bool:
        j = self.cmp_fn(self.value, other.value)
        if j != 0:
            return j < 0
        return self.seq < other.seq  # stable among equals


class PriorityQueue:
    """Pop returns the item for which less_fn says it orders before all
    others ("highest priority first" by convention of the less fns).
    ``cmp_fn`` (3-way, -1/0/1) is the cheaper protocol when the caller
    has one — identical ordering to the equivalent less_fn."""

    def __init__(self, less_fn: Optional[Callable] = None,
                 cmp_fn: Optional[Callable] = None):
        self._heap: list = []
        self._less_fn = less_fn
        self._cmp_fn = cmp_fn
        self._seq = itertools.count()

    def push(self, value) -> None:
        if self._cmp_fn is not None:
            heapq.heappush(
                self._heap, _CmpItem(value, self._cmp_fn, next(self._seq)))
        else:
            heapq.heappush(
                self._heap, _Item(value, self._less_fn, next(self._seq)))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).value

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


def make_task_queue(ssn, items, reverse: bool = False):
    """Build-then-drain task queue ordered by the session's task order:
    a SortedTaskQueue when the session exposes an equivalent sort key
    (Session.stock_task_order_key), else a comparator PriorityQueue.
    ``reverse`` inverts the order (the preempt victim cut)."""
    key = ssn.stock_task_order_key()
    if key is not None:
        return SortedTaskQueue(items, key, reverse=reverse)
    if reverse:
        q = PriorityQueue(lambda l, r: not ssn.task_order_fn(l, r))
    else:
        q = PriorityQueue(ssn.task_order_fn)
    for item in items:
        q.push(item)
    return q


class SortedTaskQueue:
    """PriorityQueue-compatible pop/empty over a batch of items sorted ONCE
    by a key function (no comparator dispatch per pair). Valid only for the
    build-then-drain pattern — push after the first pop is a bug, and the
    caller must have verified the key matches the session's comparator
    (Session.stock_task_order_key)."""

    __slots__ = ("_items", "_pos")

    def __init__(self, items, key, reverse: bool = False):
        self._items = sorted(items, key=key, reverse=reverse)
        self._pos = 0

    def pop(self):
        if self._pos >= len(self._items):
            return None
        v = self._items[self._pos]
        self._pos += 1
        return v

    def empty(self) -> bool:
        return self._pos >= len(self._items)

    def __len__(self) -> int:
        return len(self._items) - self._pos
