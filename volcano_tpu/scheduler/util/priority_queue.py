"""Heap-based priority queue over an arbitrary less-fn
(volcano pkg/scheduler/util/priority_queue.go)."""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Optional


class _Item:
    __slots__ = ("value", "less_fn", "seq")

    def __init__(self, value, less_fn, seq):
        self.value = value
        self.less_fn = less_fn
        self.seq = seq

    def __lt__(self, other: "_Item") -> bool:
        if self.less_fn is None:
            return self.seq < other.seq
        if self.less_fn(self.value, other.value):
            return True
        if self.less_fn(other.value, self.value):
            return False
        return self.seq < other.seq  # stable among equals


class PriorityQueue:
    """Pop returns the item for which less_fn says it orders before all
    others ("highest priority first" by convention of the less fns)."""

    def __init__(self, less_fn: Optional[Callable] = None):
        self._heap: list[_Item] = []
        self._less_fn = less_fn
        self._seq = itertools.count()

    def push(self, value) -> None:
        heapq.heappush(self._heap, _Item(value, self._less_fn, next(self._seq)))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).value

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)
