"""Metered fault-degradation ladder: typed backoff, per-dependency
circuit breakers, and explicit degradation rungs.

The stack already degrades honestly at each seam — per-action kernel
fallbacks (ops/solver.py, ops/evict.py, ops/session_fuse.py), the
express lane's defer-to-session contract, the remote watch re-list
retry. What was missing is the POLICY layer tying those seams together:
how hard to retry a failing dependency (capped jittered exponential
backoff, never fixed-interval hammering), when to stop asking entirely
(a circuit breaker per dependency), which explicit rung the scheduler is
on, and how it all recovers — automatically, and visible on /metrics.

Rungs, mildest first (each is the documented response to a persistently
failing dependency; see docs/DESIGN.md §15):

- ``per_action_fallback``  — a device solve failed; that action ran its
  serial oracle (the standing ops/ fallback, now counted here);
- ``serial_host_solve``    — the kernel breaker is OPEN: persistent
  device/compile failure, every action goes serial preemptively instead
  of paying a doomed dispatch + fallback per action;
- ``pipeline_disabled``    — the continuous pipeline's breaker is open
  (repeated pipelined-cycle ERRORS — speculation discards are normal
  churn and never trip it): the scheduler loop falls back to the serial
  run_once cycle until the half-open probe passes;
- ``express_disabled``     — the express lane's breaker is open (repeated
  batch errors) or the lane was parked by lease loss: arrivals fall
  through to full sessions;
- ``watch_coalesce_aggressive`` — watch fan-out lag is climbing (a
  watcher crossed half its demotion budget): the fan-out layer
  (store/flowcontrol.py) compacts EVERY delivery batch instead of only
  large catch-ups, trading event granularity for drain rate before any
  watcher has to be demoted;
- ``admission_shed``       — the intake gate (admission/intake.py) is
  actively shedding submissions: rejected-with-retry, batch before
  interactive; the gauge holds for ``shed_hold_s`` past the last shed so
  scrapers see bursts shorter than their interval;
- ``snapshot_resync_only`` — the front-door breaker is open (a demotion
  storm — watchers falling off faster than they resync): deep laggards
  stop receiving incremental catch-up streams entirely and are answered
  with the reset/re-list contract immediately, keeping the journal and
  the delivery path bounded while the herd recovers; a successful
  resync (promotion) is the half-open probe's success;
- ``session_skip``         — the remote-store breaker is open: skip
  sessions rather than schedule against an unreachable truth, with a
  BOUNDED staleness budget (after ``max_session_skips`` consecutive
  skips the next session runs regardless, so a flapping probe can never
  park the scheduler forever).

Every rung is published as ``volcano_degraded_mode{rung}`` (1 = active)
and recovery closes the breaker and clears the gauge — no operator
action required.

Determinism: backoff jitter derives from a per-instance seeded RNG (the
name, not the wall clock), and breaker cooldowns read utils/clock.now()
— the simulator's virtual clock during a sim run — so degraded-mode
decisions replay byte-identically under the same seed.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional

from volcano_tpu.scheduler import metrics
from volcano_tpu.utils import clock

RUNGS = ("per_action_fallback", "watch_coalesce_aggressive",
         "pipeline_disabled", "serial_host_solve", "express_disabled",
         "admission_shed", "snapshot_resync_only", "session_skip")


class Backoff:
    """Capped, jittered exponential backoff (full-jitter style: the delay
    is uniform in [delay*(1-jitter), delay] so synchronized retriers
    de-correlate). ``next_delay()`` advances the attempt; ``reset()`` on
    success. Deterministic per (name): the jitter RNG is seeded from the
    name, never the clock — two runs retry identically."""

    def __init__(self, name: str, base: float = 0.5, cap: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError("backoff needs base > 0, cap >= base, factor >= 1")
        self.name = name
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.attempt = 0
        self.retries = 0
        self.total_backoff_s = 0.0
        self._rng = rng if rng is not None else random.Random(
            f"volcano-backoff:{name}")

    def peek(self) -> float:
        """The un-jittered delay the next next_delay() scales from."""
        return min(self.base * (self.factor ** self.attempt), self.cap)

    def next_delay(self) -> float:
        delay = self.peek()
        if self.jitter:
            delay *= 1.0 - self.jitter * self._rng.random()
        self.attempt += 1
        self.retries += 1
        self.total_backoff_s += delay
        return delay

    def reset(self) -> None:
        self.attempt = 0

    def stats(self) -> Dict[str, float]:
        return {"attempt": self.attempt, "retries": self.retries,
                "total_backoff_s": round(self.total_backoff_s, 3)}


class CircuitBreaker:
    """Per-dependency breaker: CLOSED (healthy) -> OPEN after
    ``threshold`` consecutive failures -> HALF_OPEN one probe after
    ``cooldown_s`` -> CLOSED on probe success, OPEN again on failure.

    ``allow()`` answers "may I try this dependency now" and is what the
    callers gate on; time comes from utils/clock.now() so the simulator's
    virtual clock drives recovery deterministically."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, threshold: int = 3,
                 cooldown_s: float = 30.0):
        self.name = name
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self.stats = {"failures": 0, "opens": 0, "probes": 0, "closes": 0}

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN \
                    and clock.now() - self._opened_at >= self.cooldown_s:
                self._state = self.HALF_OPEN
                self.stats["probes"] += 1
                return True  # exactly this caller probes
            return self._state == self.HALF_OPEN

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != self.CLOSED:
                self._state = self.CLOSED
                self.stats["closes"] += 1

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self.stats["failures"] += 1
            if self._state == self.HALF_OPEN \
                    or (self._state == self.CLOSED
                        and self._failures >= self.threshold):
                self._state = self.OPEN
                self._opened_at = clock.now()
                self.stats["opens"] += 1


class DegradeLadder:
    """The per-scheduler degradation policy: one breaker per dependency
    (remote store, device kernel, express lane) plus the bounded
    session-skip budget, all metered through volcano_degraded_mode."""

    def __init__(self, store_threshold: int = 3, store_cooldown_s: float = 15.0,
                 kernel_threshold: int = 3, kernel_cooldown_s: float = 60.0,
                 express_threshold: int = 3, express_cooldown_s: float = 30.0,
                 pipeline_threshold: int = 3, pipeline_cooldown_s: float = 30.0,
                 frontdoor_threshold: int = 5,
                 frontdoor_cooldown_s: float = 10.0,
                 coalesce_hold_s: float = 10.0, shed_hold_s: float = 5.0,
                 max_session_skips: int = 5):
        self.store = CircuitBreaker("store", store_threshold,
                                    store_cooldown_s)
        self.kernel = CircuitBreaker("kernel", kernel_threshold,
                                     kernel_cooldown_s)
        self.express = CircuitBreaker("express", express_threshold,
                                      express_cooldown_s)
        self.pipeline = CircuitBreaker("pipeline", pipeline_threshold,
                                       pipeline_cooldown_s)
        # front-door breaker: failure = a watcher demotion, success = a
        # completed resync (promotion). Open = snapshot_resync_only.
        self.frontdoor = CircuitBreaker("frontdoor", frontdoor_threshold,
                                        frontdoor_cooldown_s)
        self.coalesce_hold_s = float(coalesce_hold_s)
        self.shed_hold_s = float(shed_hold_s)
        self._coalesce_until = 0.0
        self._shed_until = 0.0
        self.max_session_skips = int(max_session_skips)
        self._skips = 0
        self.counters = {"sessions_skipped": 0, "forced_sessions": 0,
                         "per_action_fallbacks": 0, "watch_demotions": 0,
                         "watch_promotions": 0, "admission_sheds": 0}

    # -- dependency reports (each publishes its rung transition) -----------

    def note_store_error(self) -> None:
        self.store.record_failure()
        self._publish()

    def note_store_ok(self) -> None:
        self.store.record_success()
        self._skips = 0
        self._publish()

    def note_kernel_failure(self) -> None:
        self.kernel.record_failure()
        self.counters["per_action_fallbacks"] += 1
        metrics.set_degraded_mode("per_action_fallback", True)
        self._publish()

    def note_kernel_ok(self) -> None:
        self.kernel.record_success()
        metrics.set_degraded_mode("per_action_fallback", False)
        self._publish()

    def note_express_error(self) -> None:
        self.express.record_failure()
        self._publish()

    def note_express_ok(self) -> None:
        self.express.record_success()
        self._publish()

    def note_pipeline_error(self) -> None:
        """A pipelined cycle CRASHED (not a speculation discard — those
        are the design working as intended and are merely counted)."""
        self.pipeline.record_failure()
        self._publish()

    def note_pipeline_ok(self) -> None:
        self.pipeline.record_success()
        self._publish()

    # -- front-door signals (watch fan-out + admission intake) --------------

    def note_watch_lag(self, lag: int, demote_lag: int) -> None:
        """A watcher's poll observed ``lag`` pending events against the
        fan-out's ``demote_lag`` budget. Crossing HALF the budget arms
        the watch_coalesce_aggressive rung for ``coalesce_hold_s`` —
        compaction ramps up BEFORE anyone has to be demoted."""
        if demote_lag > 0 and 2 * lag >= demote_lag:
            self._coalesce_until = clock.now() + self.coalesce_hold_s
            self._publish()

    def note_watch_demotion(self) -> None:
        self.counters["watch_demotions"] += 1
        self.frontdoor.record_failure()
        self._publish()

    def note_watch_promoted(self) -> None:
        """A demoted watcher completed its snapshot resync — the
        front-door breaker's success signal (and half-open probe)."""
        self.counters["watch_promotions"] += 1
        self.frontdoor.record_success()
        self._publish()

    def note_admission_shed(self) -> None:
        self.counters["admission_sheds"] += 1
        self._shed_until = clock.now() + self.shed_hold_s
        self._publish()

    def note_admission_ok(self) -> None:
        self._publish()

    # -- the gates callers consult ------------------------------------------

    def force_serial(self) -> bool:
        """True while the kernel breaker refuses device dispatches: the
        solver skips the device path (its callers run the serial oracle)
        instead of paying a doomed dispatch per action. allow() doubles as
        the half-open probe — one dispatch is let through after the
        cooldown, and its success closes the breaker."""
        return not self.kernel.allow()

    def express_allowed(self) -> bool:
        return self.express.allow()

    def pipeline_allowed(self) -> bool:
        """True while the pipelined loop may run; False = the
        pipeline_disabled rung — the scheduler runs the serial run_once
        cycle (byte-for-byte the VOLCANO_TPU_PIPELINE=0 oracle) until the
        half-open probe lets one pipelined cycle prove itself again."""
        return self.pipeline.allow()

    def watch_coalesce_aggressive(self) -> bool:
        """True while delivery batches should be compacted regardless of
        size: the lag signal armed the hold window, or the front-door
        breaker is already open (resync-only implies coalesce-hard)."""
        return clock.now() < self._coalesce_until \
            or self.frontdoor.state != CircuitBreaker.CLOSED

    def watch_resync_only(self) -> bool:
        """True while deep laggards must be answered with an immediate
        reset/re-list instead of an incremental catch-up stream. allow()
        doubles as the half-open probe: after the cooldown exactly one
        laggard gets an incremental attempt, and its completed resync
        (note_watch_promoted) closes the breaker."""
        return not self.frontdoor.allow()

    def should_skip_session(self) -> bool:
        """True while the store breaker is open AND the staleness budget
        holds; the budget guarantees a bounded-staleness session even
        under a permanently failing probe."""
        if self.store.allow():
            self._skips = 0
            return False
        if self._skips >= self.max_session_skips:
            self.counters["forced_sessions"] += 1
            self._skips = 0
            return False
        self._skips += 1
        self.counters["sessions_skipped"] += 1
        return True

    # -- metering ------------------------------------------------------------

    def rung(self) -> str:
        """The most severe active rung ('' when healthy). Pure state
        inspection — allow() would consume a half-open probe slot."""
        if self.store.state != CircuitBreaker.CLOSED or self._skips:
            return "session_skip"
        if self.frontdoor.state != CircuitBreaker.CLOSED:
            return "snapshot_resync_only"
        if clock.now() < self._shed_until:
            return "admission_shed"
        if self.express.state != CircuitBreaker.CLOSED:
            return "express_disabled"
        if self.kernel.state != CircuitBreaker.CLOSED:
            return "serial_host_solve"
        if self.pipeline.state != CircuitBreaker.CLOSED:
            return "pipeline_disabled"
        if clock.now() < self._coalesce_until:
            return "watch_coalesce_aggressive"
        return ""

    def _publish(self) -> None:
        metrics.set_degraded_mode(
            "serial_host_solve",
            self.kernel.state != CircuitBreaker.CLOSED)
        metrics.set_degraded_mode(
            "express_disabled",
            self.express.state != CircuitBreaker.CLOSED)
        metrics.set_degraded_mode(
            "session_skip", self.store.state != CircuitBreaker.CLOSED)
        metrics.set_degraded_mode(
            "pipeline_disabled",
            self.pipeline.state != CircuitBreaker.CLOSED)
        now = clock.now()
        metrics.set_degraded_mode(
            "watch_coalesce_aggressive",
            now < self._coalesce_until
            or self.frontdoor.state != CircuitBreaker.CLOSED)
        metrics.set_degraded_mode("admission_shed", now < self._shed_until)
        metrics.set_degraded_mode(
            "snapshot_resync_only",
            self.frontdoor.state != CircuitBreaker.CLOSED)

    def stats(self) -> Dict[str, object]:
        return {
            "rung": self.rung(),
            "counters": dict(self.counters),
            "breakers": {b.name: {"state": b.state, **b.stats}
                         for b in (self.store, self.kernel, self.express,
                                   self.pipeline, self.frontdoor)},
        }


# Process-default ladder: the seams that cannot see a Scheduler instance
# (ops/solver.py device-failure hooks) report here; a Scheduler adopts it
# so its loop and the kernel share one policy. reset() restores pristine
# state (sim runs and tests call it alongside metrics.reset()).

_default: Optional[DegradeLadder] = None
_default_lock = threading.Lock()


def default_ladder() -> DegradeLadder:
    global _default
    ladder = _default
    if ladder is None:
        with _default_lock:
            if _default is None:
                _default = DegradeLadder()
            ladder = _default
    return ladder


def reset() -> None:
    global _default
    with _default_lock:
        _default = None


def note_kernel_failure() -> None:
    default_ladder().note_kernel_failure()


def note_kernel_ok() -> None:
    default_ladder().note_kernel_ok()


def force_serial() -> bool:
    return default_ladder().force_serial()
