"""Scheduler metrics under namespace ``volcano``
(volcano pkg/scheduler/metrics/metrics.go:37-121).

Self-contained histogram/counter/gauge registry rendering the Prometheus text
exposition format, with the reference's exact series names:

- volcano_e2e_scheduling_latency_milliseconds (histogram, 5ms*2^k buckets)
- volcano_plugin_scheduling_latency_microseconds{plugin,OnSession}
- volcano_action_scheduling_latency_microseconds{action}
- volcano_task_scheduling_latency_microseconds
- volcano_schedule_attempts_total{result}
- volcano_pod_preemption_victims / volcano_total_preemption_attempts
- volcano_unschedule_task_count{job_id} / volcano_unschedule_job_count
- volcano_job_retry_counts{job_id}
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_NAMESPACE = "volcano"


class Histogram:
    def __init__(self, name: str, help_: str, buckets: List[float], label_names=()):
        self.name = name
        self.help = help_
        self.buckets = sorted(buckets)
        self.label_names = tuple(label_names)
        self._data: Dict[Tuple[str, ...], Tuple[List[int], float, int]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Tuple[str, ...] = ()) -> None:
        with self._lock:
            counts, total, n = self._data.get(labels, ([0] * len(self.buckets), 0.0, 0))
            counts = list(counts)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._data[labels] = (counts, total + value, n + 1)

    def snapshot(self):
        with self._lock:
            return dict(self._data)


class Counter:
    def __init__(self, name: str, help_: str, label_names=()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._data: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Tuple[str, ...] = (), value: float = 1.0) -> None:
        with self._lock:
            self._data[labels] = self._data.get(labels, 0.0) + value

    def get(self, labels: Tuple[str, ...] = ()) -> float:
        with self._lock:
            return self._data.get(labels, 0.0)


class Gauge:
    """A set-to-current-value metric (pending pods, queue depth): unlike a
    Counter it can move both ways, so scrapers read the instantaneous
    level instead of a monotone total."""

    def __init__(self, name: str, help_: str, label_names=()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._data: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Tuple[str, ...] = ()) -> None:
        with self._lock:
            self._data[labels] = float(value)

    def inc(self, labels: Tuple[str, ...] = (), value: float = 1.0) -> None:
        with self._lock:
            self._data[labels] = self._data.get(labels, 0.0) + value

    def get(self, labels: Tuple[str, ...] = ()) -> float:
        with self._lock:
            return self._data.get(labels, 0.0)


class Registry:
    def __init__(self):
        ms = [0.005 * (2**k) for k in range(10)]  # 5ms..~5s, in seconds
        us = [5e-6 * (2**k) for k in range(12)]
        self.e2e_latency = Histogram(
            f"{_NAMESPACE}_e2e_scheduling_latency_milliseconds",
            "E2e scheduling latency in milliseconds", ms)
        self.plugin_latency = Histogram(
            f"{_NAMESPACE}_plugin_scheduling_latency_microseconds",
            "Plugin scheduling latency in microseconds", us, ("plugin", "OnSession"))
        self.action_latency = Histogram(
            f"{_NAMESPACE}_action_scheduling_latency_microseconds",
            "Action scheduling latency in microseconds", us, ("action",))
        self.task_latency = Histogram(
            f"{_NAMESPACE}_task_scheduling_latency_microseconds",
            "Task scheduling latency in microseconds", us)
        self.schedule_attempts = Counter(
            f"{_NAMESPACE}_schedule_attempts_total",
            "Num of attempts to schedule pods, by result", ("result",))
        self.preemption_victims = Counter(
            f"{_NAMESPACE}_pod_preemption_victims", "Number of preemption victims")
        self.preemption_attempts = Counter(
            f"{_NAMESPACE}_total_preemption_attempts", "Total preemption attempts")
        self.unschedule_task_count = Counter(
            f"{_NAMESPACE}_unschedule_task_count", "Unschedulable tasks per job", ("job_id",))
        self.unschedule_job_count = Counter(
            f"{_NAMESPACE}_unschedule_job_count", "Number of unschedulable jobs")
        self.job_retry_counts = Counter(
            f"{_NAMESPACE}_job_retry_counts", "Job retries", ("job_id",))
        # express lane (volcano_tpu/express): optimistic placements
        # between sessions, the session-time reverts, and the fast-path
        # latency distribution (sub-10 ms is the design envelope, so the
        # buckets resolve single milliseconds)
        self.express_placements = Counter(
            f"{_NAMESPACE}_express_placements_total",
            "Tasks optimistically placed by the express lane")
        self.express_reverted = Counter(
            f"{_NAMESPACE}_express_reverted_total",
            "Express placements reverted by full-session reconciliation")
        self.express_deferred = Counter(
            f"{_NAMESPACE}_express_deferred_total",
            "Arrivals the express lane deferred to a full session")
        self.express_latency = Histogram(
            f"{_NAMESPACE}_express_latency_seconds",
            "Express run-once latency in seconds",
            [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25])
        # HA failover (scheduler/ha.py + store fencing): leadership churn,
        # the fenced-write rejection total the failover auditor balances
        # against the store's own accounting, and the degradation-ladder
        # rung gauge (scheduler/degrade.py) — one labeled series per rung,
        # 1 while that rung is active
        self.leader_transitions = Counter(
            f"{_NAMESPACE}_leader_transitions_total",
            "Leadership acquisitions observed by this process")
        self.fenced_writes_rejected = Counter(
            f"{_NAMESPACE}_fenced_writes_rejected_total",
            "Writes rejected for carrying a stale lease epoch")
        self.degraded_mode = Gauge(
            f"{_NAMESPACE}_degraded_mode",
            "Degradation-ladder rung activity (1 = active)", ("rung",))
        # continuous pipeline (volcano_tpu/pipeline): sustained throughput
        # (the headline the pipelined loop binds on), per-reason
        # speculation discards (an invalidated stage is NEVER applied —
        # the counter is the proof the discard path ran), and the host
        # wall overlapped with an in-flight speculative device solve
        self.pipeline_sessions_per_sec = Gauge(
            f"{_NAMESPACE}_pipeline_sessions_per_sec",
            "Sustained committed sessions per wall second through the "
            "pipelined loop")
        self.pipeline_spec_discards = Counter(
            f"{_NAMESPACE}_pipeline_spec_discards_total",
            "Speculative solve-ahead stages discarded before apply, "
            "by invalidation reason", ("reason",))
        self.pipeline_spec_commits = Counter(
            f"{_NAMESPACE}_pipeline_spec_commits_total",
            "Speculative solve-ahead stages committed, by kind: quiet "
            "(fingerprint unmoved) vs readset (state moved but every "
            "delta proven disjoint from the stage's read set)", ("kind",))
        self.pipeline_overlap = Histogram(
            f"{_NAMESPACE}_pipeline_overlap_seconds",
            "Host work overlapped with an in-flight speculative device "
            "solve, per committed cycle",
            [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0])
        # device-path honesty fallbacks (ROADMAP item 4): every envelope
        # miss that dropped a session/action back to the serial oracle,
        # labeled by kind (fuse, evict_preempt, evict_reclaim,
        # evict_backfill). The sim auditor audits these as RATES against
        # per-scenario budgets, so an envelope regression fails the gate
        # exactly like a parity regression
        self.device_fallbacks = Counter(
            f"{_NAMESPACE}_device_fallbacks_total",
            "Device-path honesty fallbacks to the serial oracle, by kind",
            ("kind",))
        # front-door overload (store/flowcontrol.py + admission/intake.py):
        # per-class watch fan-out lag, delivery-side coalescing, and the
        # intake gate's shed/retry-after accounting — the meters the
        # front_door_storm auditor budgets ride on
        self.watch_queue_depth = Gauge(
            f"{_NAMESPACE}_watch_queue_depth",
            "Pending watch events behind the slowest observed cursor, "
            "per watcher class", ("watcher_class",))
        self.watch_events_coalesced = Counter(
            f"{_NAMESPACE}_watch_events_coalesced_total",
            "Watch events collapsed by delivery-side batch compaction")
        self.admission_shed = Counter(
            f"{_NAMESPACE}_admission_shed_total",
            "Submissions shed by the intake gate, by reason", ("reason",))
        self.admission_retry_after = Histogram(
            f"{_NAMESPACE}_admission_retry_after_seconds",
            "Retry-after hints handed to shed submissions, in seconds",
            [0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0])
        # instantaneous cluster levels (set each cycle; the sim harness and
        # the scheduler loop both publish through these)
        self.pending_pods = Gauge(
            f"{_NAMESPACE}_pending_pods", "Pods currently awaiting placement")
        self.queue_depth = Gauge(
            f"{_NAMESPACE}_queue_depth",
            "PodGroups currently pending or inqueue, per queue", ("queue",))
        self.sessions_run = Gauge(
            f"{_NAMESPACE}_sessions_run",
            "Scheduler sessions completed since process start")


_registry: Optional[Registry] = None
_registry_lock = threading.Lock()


def registry() -> Registry:
    # double-checked fast path: per-victim hot loops (preempt/reclaim)
    # call through here thousands of times per session, and the global
    # assignment below is atomic under the GIL
    global _registry
    r = _registry
    if r is not None:
        return r
    with _registry_lock:
        if _registry is None:
            _registry = Registry()
        return _registry


def reset() -> None:
    global _registry
    with _registry_lock:
        _registry = None


# -- recording helpers (metrics.go:123-191) ---------------------------------


def update_e2e_duration(seconds: float) -> None:
    registry().e2e_latency.observe(seconds)


def update_plugin_duration(plugin: str, on_session: str, seconds: float) -> None:
    registry().plugin_latency.observe(seconds, (plugin, on_session))


def update_action_duration(action: str, seconds: float) -> None:
    registry().action_latency.observe(seconds, (action,))


def update_task_schedule_duration(seconds: float) -> None:
    registry().task_latency.observe(seconds)


def register_schedule_attempts(result: str) -> None:
    registry().schedule_attempts.inc((result,))


def update_preemption_victims(n: int) -> None:
    registry().preemption_victims.inc(value=n)


def register_preemption_attempts(n: int = 1) -> None:
    registry().preemption_attempts.inc(value=n)


def update_unschedule_task_count(job_id: str, n: int) -> None:
    registry().unschedule_task_count.inc((job_id,), n)


def update_unschedule_job_count(n: int = 1) -> None:
    registry().unschedule_job_count.inc(value=n)


def register_job_retry(job_id: str) -> None:
    registry().job_retry_counts.inc((job_id,))


def set_pending_pods(n: int) -> None:
    registry().pending_pods.set(n)


def set_queue_depth(queue: str, n: int) -> None:
    registry().queue_depth.set(n, (queue,))


def set_sessions_run(n: int) -> None:
    registry().sessions_run.set(n)


def register_express_placements(n: int = 1) -> None:
    registry().express_placements.inc(value=n)


def register_express_reverted(n: int = 1) -> None:
    registry().express_reverted.inc(value=n)


def register_express_deferred(n: int = 1) -> None:
    registry().express_deferred.inc(value=n)


def observe_express_latency(seconds: float) -> None:
    registry().express_latency.observe(seconds)


def register_leader_transition(n: int = 1) -> None:
    registry().leader_transitions.inc(value=n)


def register_fenced_write(n: int = 1) -> None:
    registry().fenced_writes_rejected.inc(value=n)


def set_degraded_mode(rung: str, active: bool) -> None:
    registry().degraded_mode.set(1.0 if active else 0.0, (rung,))


def set_pipeline_sessions_per_sec(v: float) -> None:
    registry().pipeline_sessions_per_sec.set(v)


def register_fallback(kind: str, n: int = 1) -> None:
    registry().device_fallbacks.inc((kind,), n)


def register_pipeline_spec_discard(reason: str, n: int = 1) -> None:
    registry().pipeline_spec_discards.inc((reason,), n)


def register_pipeline_spec_commit(kind: str, n: int = 1) -> None:
    registry().pipeline_spec_commits.inc((kind,), n)


def observe_pipeline_overlap(seconds: float) -> None:
    registry().pipeline_overlap.observe(seconds)


def set_watch_queue_depth(watcher_class: str, n: int) -> None:
    registry().watch_queue_depth.set(n, (watcher_class,))


def register_watch_coalesced(n: int = 1) -> None:
    registry().watch_events_coalesced.inc(value=n)


def register_admission_shed(reason: str, n: int = 1) -> None:
    registry().admission_shed.inc((reason,), n)


def observe_admission_retry_after(seconds: float) -> None:
    registry().admission_retry_after.observe(seconds)


# -- exposition -------------------------------------------------------------


def render() -> str:
    """Prometheus text format for the /metrics endpoint analog."""
    r = registry()
    lines: List[str] = []
    for h in (r.e2e_latency, r.plugin_latency, r.action_latency,
              r.task_latency, r.express_latency, r.pipeline_overlap,
              r.admission_retry_after):
        lines.append(f"# HELP {h.name} {h.help}")
        lines.append(f"# TYPE {h.name} histogram")
        for labels, (counts, total, n) in h.snapshot().items():
            label_str = ",".join(f'{k}="{v}"' for k, v in zip(h.label_names, labels))
            for b, c in zip(h.buckets, counts):
                le = f'le="{b}"'
                full = ",".join(x for x in (label_str, le) if x)
                lines.append(f"{h.name}_bucket{{{full}}} {c}")
            # the +Inf bucket is mandatory in the exposition format (its
            # value == _count); scrapers compute quantiles from it
            inf = ",".join(x for x in (label_str, 'le="+Inf"') if x)
            lines.append(f"{h.name}_bucket{{{inf}}} {n}")
            suffix = f"{{{label_str}}}" if label_str else ""
            lines.append(f"{h.name}_sum{suffix} {total}")
            lines.append(f"{h.name}_count{suffix} {n}")
    for c in (
        r.schedule_attempts, r.preemption_victims, r.preemption_attempts,
        r.unschedule_task_count, r.unschedule_job_count, r.job_retry_counts,
        r.express_placements, r.express_reverted, r.express_deferred,
        r.leader_transitions, r.fenced_writes_rejected,
        r.pipeline_spec_discards, r.pipeline_spec_commits,
        r.watch_events_coalesced, r.admission_shed,
    ):
        lines.append(f"# HELP {c.name} {c.help}")
        lines.append(f"# TYPE {c.name} counter")
        with c._lock:
            for labels, v in c._data.items():
                label_str = ",".join(f'{k}="{v2}"' for k, v2 in zip(c.label_names, labels))
                suffix = f"{{{label_str}}}" if label_str else ""
                lines.append(f"{c.name}{suffix} {v}")
    for g in (r.pending_pods, r.queue_depth, r.sessions_run,
              r.degraded_mode, r.pipeline_sessions_per_sec,
              r.watch_queue_depth):
        lines.append(f"# HELP {g.name} {g.help}")
        lines.append(f"# TYPE {g.name} gauge")
        with g._lock:
            for labels, v in g._data.items():
                label_str = ",".join(f'{k}="{v2}"' for k, v2 in zip(g.label_names, labels))
                suffix = f"{{{label_str}}}" if label_str else ""
                lines.append(f"{g.name}{suffix} {v}")
    return "\n".join(lines) + "\n"
