"""Full-session reconciliation — the fairness/preemption authority's
verdict on every optimistic express bind.

Runs at the head of every full session's action chain
(framework.run_actions), after plugins opened (so proportion's deserved
shares and the gang/job-ready machinery are live). For every outstanding
token recorded since the previous session:

- **confirm** when the session agrees: the job still exists, every
  express-bound task is still allocated on its recorded node, the gang is
  ready (min_available holds), and the job's queue is not overused (the
  proportion plugin's deserved-share gate — the check express itself
  deliberately does not model);
- **revert** otherwise: the surviving express binds become ordinary
  evictions through the existing Statement machinery (stmt.evict ->
  commit -> cache.evict -> evictor), so events, cache accounting,
  SnapshotKeeper dirty-sets, and metrics land exactly as a preemption
  would, the freed capacity is visible to THIS session's own actions
  (the reconciler runs before allocate), and the job controller's normal
  recovery resubmits the evicted pods for the full path to place.
  Reverted jobs are denylisted from the lane — the full session owns
  them from then on;
- tokens whose tasks all vanished (pod deleted / completed in the
  window) resolve as terminal lifecycle churn — nothing to keep, nothing
  to reclaim.

Every token is resolved within ONE session — the invariant the
simulator's auditor now checks continuously (sim/auditor.py
express_reconciliation rule).

Continuous-pipeline interaction (volcano_tpu/pipeline): a SPECULATIVE
session — opened and dispatched ahead of the previous cycle's close —
never reconciles; only the session that actually COMMITS does, and it
bumps ``lane.session_seq`` exactly once. Tokens carry the lane's
``commit_epoch`` at mint time, and the pipeline seals that epoch into its
dispatch fingerprint: an express commit landing while a speculative solve
is in flight moves the epoch, the speculative stage is discarded unapplied
(``pipeline_spec_discard{reason="express_commit"}``), and the token drains
through the re-run — the session that commits, never the one in flight.
The pipeline also refuses to START speculating while tokens are
outstanding (their reverts must free capacity BEFORE the solve encodes),
so a reconcile verdict is always computed by the same session whose
placements it shapes.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from volcano_tpu.api.types import allocated_status
from volcano_tpu.scheduler import metrics

logger = logging.getLogger(__name__)


def reconcile_session(ssn, after_epoch: Optional[int] = None) \
        -> Optional[Dict]:
    """Resolve every outstanding express token against this session.
    No-op (None) when no lane is attached.

    ``after_epoch`` — the committing pipeline stage's SEALED commit
    epoch: tokens minted after it (token.epoch > after_epoch) reference
    jobs this session's snapshot never contained, so reconciling them
    here would wrongly revert fresh binds ("job left the snapshot").
    They stay outstanding — counted as ``deferred`` — and resolve in the
    NEXT session, which the pipeline guarantees runs on a fresh snapshot
    (speculation refuses to start while tokens are outstanding)."""
    lane = getattr(ssn.cache, "express_lane", None)
    if lane is None:
        return None
    stats = {"confirmed": 0, "reverted": 0, "terminal": 0,
             "reverted_tasks": 0, "deferred": 0}
    lane.last_reverts = []
    for job_uid in sorted(lane.outstanding):
        if after_epoch is not None \
                and lane.outstanding[job_uid].epoch > after_epoch:
            stats["deferred"] += 1
            continue
        token = lane.outstanding.pop(job_uid)
        job = ssn.jobs.get(job_uid)
        live = []      # (session task, recorded node) still express-bound
        missing = 0
        for uid in sorted(token.binds):
            key, node_name = token.binds[uid]
            task = job.tasks.get(uid) if job is not None else None
            if task is None:
                missing += 1  # lifecycle churn: pod completed/deleted
                continue
            if allocated_status(task.status) and task.node_name == node_name:
                live.append((task, node_name))
            else:
                missing += 1  # moved by something with authority already
        if not live:
            stats["terminal"] += 1
            lane._count("terminal", 1)
            continue
        verdict = _verdict(ssn, job, token, missing)
        if verdict is None:
            stats["confirmed"] += 1
            lane._count("reconciled", 1)
            continue
        stmt = ssn.statement()
        for task, node_name in live:
            stmt.evict(task, f"express-reconcile: {verdict}")
            lane.last_reverts.append((job_uid, task.key, node_name))
        stmt.commit()
        lane.denylist.add(job_uid)
        stats["reverted"] += 1
        stats["reverted_tasks"] += len(live)
        lane._count("reverted", len(live))
        logger.info("express revert %s (%d tasks): %s",
                    job_uid, len(live), verdict)
    if stats["reverted_tasks"]:
        metrics.register_express_reverted(stats["reverted_tasks"])
    lane.session_seq += 1
    return stats


def _verdict(ssn, job, token, missing: int) -> Optional[str]:
    """None to confirm, else the revert reason."""
    if job is None:
        return "job left the snapshot with live binds"
    if missing:
        # part of the gang vanished; keeping the remainder would risk a
        # standing half-gang — the session's gang gate decides
        if not ssn.job_ready(job):
            return "gang lost members below min_available"
    if not ssn.job_ready(job):
        return "gang not ready under the session's job-ready gate"
    queue = ssn.queues.get(job.queue)
    if queue is None:
        return "queue no longer exists"
    if ssn.overused(queue):
        return "queue overused under the session's deserved shares"
    return None
