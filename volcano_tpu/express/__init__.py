"""volcano_tpu/express — event-driven express lane: sub-10 ms incremental
placement for interactive arrivals between full sessions, reconciled by
the next full session (the fairness/preemption authority).

Modules:
- trigger.py   — watch-triggered arrival queue + eligibility envelope +
                 the run-once fast path (ExpressLane);
- encode.py    — dirty-row live node axis + device buffer cache;
- place.py     — the one-dispatch narrow windowed round (jax);
- commit.py    — optimistic validate-then-commit via the real cache
                 effectors;
- reconcile.py — full-session confirm/revert of every optimistic bind.

Only place.py (and ExpressState.stage) require jax; everything else runs
on a jax-free host, where the lane simply defers every arrival.
"""

from volcano_tpu.express.trigger import (  # noqa: F401
    EXPRESS_MAX_GANG,
    EXPRESS_MAX_TASKS,
    EXPRESS_SAFE_PLUGINS,
    ExpressLane,
    ExpressToken,
)
from volcano_tpu.express.reconcile import reconcile_session  # noqa: F401
