"""Optimistic commit — express placements through the real cache
effectors, Omega-style validate-then-commit.

The kernel decided against a snapshot of the live axis; between that
snapshot and the commit, watch events may have moved the cluster. Each
job's placements are therefore re-validated under the cache lock against
the LIVE NodeInfo accounting (the same ``resreq.less_equal(idle)`` gate
``NodeInfo.add_task`` enforces) before any bind dispatches; a job that no
longer fits is deferred whole — express never half-commits a gang and
never lets an optimistic bind trip a node into OutOfSync.

Surviving placements go through ``cache.bind`` — the exact effector the
Statement commit path uses (statement._commit_allocate -> ssn.cache.bind):
cache job/node accounting flips to BINDING, the SnapshotKeeper marks the
touched job+nodes (which also feeds the express state's dirty shadow for
the next refresh), the binder dispatches, and the Scheduled event is
recorded. Each committed job records an ExpressToken; the next full
session confirms or reverts it (express/reconcile.py).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

import numpy as np

from volcano_tpu.api.types import TaskStatus
from volcano_tpu.express.trigger import ExpressToken
from volcano_tpu.store import FencedError
from volcano_tpu.utils import clock

logger = logging.getLogger(__name__)


def commit_batch(cache, lane, jobs: List[Tuple[object, list]],
                 assign: np.ndarray, node_names: List[str]) -> Tuple[int, int]:
    """Validate + bind the batch. Returns (placed tasks, deferred jobs)."""
    placed = 0
    deferred = 0
    ti = 0
    plans = []
    with cache._lock:
        for job, tasks in jobs:
            picks = assign[ti: ti + len(tasks)]
            ti += len(tasks)
            if (picks < 0).any():
                deferred += 1  # kernel deferred (infeasible / gang strip)
                continue
            plan = _validate(cache, job, tasks, picks, node_names)
            if plan is None:
                deferred += 1
                continue
            plans.append((job, plan))
    # binds run OUTSIDE the cache lock: cache.bind takes the lock itself,
    # and the binder's store write dispatches synchronous watch callbacks
    # whose handlers re-enter the cache — holding the lock across that is
    # the ABBA inversion VT003 exists to prevent
    fenced = False
    for ji, (job, plan) in enumerate(plans):
        binds: Dict[str, Tuple[str, str]] = {}
        ok = True
        for task, node_name in plan:
            try:
                cache.bind(task, node_name)
            except FencedError:
                # the lease moved mid-commit (a deposed leader's express
                # batch): the store fenced this bind, so STOP the whole
                # batch and park the lane — every remaining write would
                # burn one rejection to learn the same thing. Binds that
                # already landed belong to this job's token below; the
                # NEW leader's first session reconciles (and reverts)
                # them through the ordinary token drain.
                logger.warning(
                    "express commit fenced (lease lost) at %s; parking "
                    "lane", task.uid)
                lane.park("lease_lost")
                ok = False
                fenced = True
                break
            except Exception:
                # a raced mutation beat the bind; the remainder of this
                # gang is NOT dispatched — reconcile reverts the partial
                logger.exception("express bind failed for %s", task.uid)
                ok = False
                break
            binds[task.uid] = (task.key, node_name)
            placed += 1
        if binds:
            lane.commit_epoch += 1
            lane.outstanding[job.uid] = ExpressToken(
                job_uid=job.uid, binds=binds, seq=lane.session_seq,
                stamp=clock.now(), epoch=lane.commit_epoch)
        if not ok:
            deferred += 1
        if fenced:
            deferred += len(plans) - ji - 1  # undispatched remainder
            break
    return placed, deferred


def _validate(cache, job, tasks, picks, node_names):
    """Live-state re-validation for one job (caller holds the cache
    lock). Returns [(cache task, node name)] or None to defer. Validation
    charges a scratch tally per node so two batch tasks aimed at one node
    are checked against their COMBINED request."""
    cache_job = cache.jobs.get(job.uid)
    if cache_job is None:
        return None
    plan = []
    tallies: Dict[str, object] = {}
    for task, ni in zip(tasks, picks.tolist()):
        if ni < 0 or ni >= len(node_names):
            return None
        ct = cache_job.tasks.get(task.uid)
        if ct is None or ct.status != TaskStatus.PENDING or ct.node_name:
            return None  # raced: task moved since classification
        name = node_names[ni]
        node = cache.nodes.get(name)
        if node is None or not node.ready():
            return None
        tally = tallies.get(name)
        if tally is None:
            tally = tallies[name] = ct.resreq.clone()
        else:
            tally.add(ct.resreq)
        if not tally.less_equal(node.idle):
            return None
        plan.append((ct, name))
    return plan
