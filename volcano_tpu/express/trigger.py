"""Express lane — watch-triggered queue, eligibility envelope, and the
sub-10 ms run-once path.

Arrivals are event-driven: the SchedulerCache's pod/podgroup handlers
notify the lane (cache.set_arrival_listener) as they mirror the watch
stream, the lane enqueues the owning job and sets its wake event, and the
scheduler loop (or the simulator's express slice, or bench --express)
services the queue between full sessions. The fast path is:

    drain -> classify (cache lock) -> refresh live axis (dirty rows only)
    -> one device dispatch (place.solve_express) -> optimistic commit
    through the real cache effectors -> reconciliation token

Eligibility envelope (everything else falls through to the next full
session, counted per reason — the honesty contract tested by
tests/test_express.py):

- the session conf's plugins are all express-modeled (no binpack, no
  custom plugins) — checked once at attach;
- the PodGroup exists, is admitted (Inqueue/Running), its queue exists;
- small jobs only: <= EXPRESS_MAX_TASKS tasks, min_available <=
  EXPRESS_MAX_GANG (non-gang or tiny gang);
- cpu+mem requests only (no scalar resources), non-empty (BestEffort
  stays with backfill), pods are <plain> (no selectors/affinity/
  tolerations), no host ports, no pod affinity, no PVC volumes;
- jobs the reconciler ever reverted are denylisted — the full session
  owns them from then on (no optimistic-revert livelock).

Express has NO preemption rights and no deserved-share model: it places
onto genuinely idle capacity or not at all, and the next full session is
the fairness/preemption authority (express/reconcile.py).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from volcano_tpu.api import objects
from volcano_tpu.api.pod_traits import pod_encode_traits
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.express.encode import ExpressState
from volcano_tpu.scheduler import metrics

logger = logging.getLogger(__name__)

EXPRESS_MAX_TASKS = 8
EXPRESS_MAX_GANG = 4

# plugins whose allocate-time semantics the express scorer + reconciler
# model; any other name in the conf disables the lane wholesale (same
# honesty gate as solver.ROUNDS_SAFE_PLUGINS)
EXPRESS_SAFE_PLUGINS = frozenset({
    "tpuscore", "priority", "gang", "drf", "predicates", "proportion",
    "nodeorder",
})

_ADMITTED = (objects.PodGroupPhase.INQUEUE, objects.PodGroupPhase.RUNNING)


@dataclass
class ExpressToken:
    """One optimistic commit awaiting full-session reconciliation."""

    job_uid: str
    binds: Dict[str, Tuple[str, str]]  # task uid -> (task key, node name)
    seq: int                           # lane.session_seq at commit time
    stamp: float = 0.0
    # lane.commit_epoch at commit time: the continuous pipeline's
    # speculative solve-ahead seals this epoch at dispatch — a token
    # minted after the seal proves an express commit landed on state the
    # in-flight solve already read, so the SPECULATIVE session is
    # discarded and the token reconciles against the session that
    # actually commits (pipeline/driver.py fingerprint)
    epoch: int = 0


@dataclass
class ExpressReport:
    queued: int = 0
    placed: int = 0
    deferred: int = 0
    batches: int = 0
    full_sweep_steps: int = 0
    ms: float = 0.0
    reasons: Dict[str, int] = field(default_factory=dict)
    profile: Dict[str, object] = field(default_factory=dict)

    def as_dict(self) -> Dict:
        return {"queued": self.queued, "placed": self.placed,
                "deferred": self.deferred, "batches": self.batches,
                "full_sweep_steps": self.full_sweep_steps,
                "ms": round(self.ms, 3),
                "reasons": dict(sorted(self.reasons.items())),
                "profile": self.profile}


class ExpressLane:
    """The event-driven express lane for one SchedulerCache."""

    def __init__(self, cache, max_tasks: int = EXPRESS_MAX_TASKS,
                 max_gang: int = EXPRESS_MAX_GANG):
        self.cache = None
        self.max_tasks = max_tasks
        self.max_gang = max_gang
        self.enabled = True
        self._qlock = threading.Lock()
        self._queue: deque = deque()
        self._queued: set = set()
        self.wake = threading.Event()
        self.outstanding: Dict[str, ExpressToken] = {}
        self.denylist: set = set()
        # failover hygiene + degradation: a parked lane defers every
        # arrival to full sessions (lease loss parks it; re-acquire/
        # promote unparks), and the error breaker auto-parks after
        # repeated batch failures, recovering via its half-open probe
        # (the express_disabled rung, scheduler/degrade.py)
        self._park_reason: Optional[str] = None
        from volcano_tpu.scheduler.degrade import CircuitBreaker

        self.breaker = CircuitBreaker("express-lane", threshold=3,
                                      cooldown_s=30.0)
        # (job_uid, task_key, node_name) triples from the most recent
        # reconcile's reverts — the auditor's zero-residue probe
        self.last_reverts: List[Tuple[str, str, str]] = []
        self.session_seq = 0
        # monotone commit counter (one bump per committed batch): the
        # pipeline fingerprint's express component — cheaper to compare
        # than the outstanding-token dict, and it moves even for tokens
        # that resolve terminally before the check
        self.commit_epoch = 0
        self.counters = {"arrivals": 0, "placed": 0, "deferred": 0,
                         "reconciled": 0, "reverted": 0, "terminal": 0,
                         "batches": 0, "errors": 0}
        self.latencies_ms: List[float] = []
        self.state: Optional[ExpressState] = None
        if cache is not None:
            self.attach(cache)

    # -- wiring ------------------------------------------------------------

    def attach(self, cache=None) -> None:
        """Bind to (or re-bind after a restart to) a SchedulerCache:
        install the arrival listener, register the keeper shadow, and
        expose the lane for the session-time reconciler. Outstanding
        tokens and counters survive a re-attach (crash recovery: the
        binds are durable in the store; the next session still owes them
        a verdict)."""
        old_stats = None
        if cache is not None:
            if self.state is not None:
                old_stats = dict(self.state.stats)
                try:
                    self.state.detach()
                except Exception:  # pragma: no cover - old cache torn down
                    pass
            self.cache = cache
            self.state = None
        cache = self.cache
        cache.express_lane = self
        cache.set_arrival_listener(self.note_arrival)
        if self.state is None:
            self.state = ExpressState(cache)
            if old_stats:
                # cumulative across crash-recovery re-attaches: the lane
                # is one continuous series even when the cache is not
                for k, v in old_stats.items():
                    self.state.stats[k] += v

    def park(self, reason: str = "parked") -> None:
        """Suspend the fast path (arrivals defer to full sessions) without
        losing state: outstanding tokens still owe the next session a
        verdict, the queue keeps accumulating, and the device buffers stay
        warm for unpark. Called on lease loss — a deposed leader must not
        keep optimistically binding — and by the error breaker."""
        self._park_reason = reason

    def unpark(self) -> None:
        self._park_reason = None
        if self.has_pending():
            self.wake.set()

    @property
    def parked(self) -> bool:
        return self._park_reason is not None

    def set_tiers(self, tiers) -> None:
        """Gate the lane on the session conf: any plugin outside the
        express-modeled set disables the fast path entirely (arrivals then
        fall through to full sessions, counted)."""
        names = {p.name for tier in tiers for p in tier.plugins}
        unknown = sorted(names - EXPRESS_SAFE_PLUGINS)
        self.enabled = not unknown
        if unknown:
            logger.info("express lane disabled: unmodeled plugins %s",
                        unknown)

    # -- arrivals (called under the cache lock — enqueue only) -------------

    def note_arrival(self, job_uid: str) -> None:
        if not job_uid:
            return
        with self._qlock:
            self.counters["arrivals"] += 1
            if job_uid not in self._queued:
                self._queued.add(job_uid)
                self._queue.append(job_uid)
        self.wake.set()

    def _count(self, key: str, n: int) -> None:
        """Counter bumps under _qlock: note_arrival increments
        ``counters`` from the watch-handler thread, the lane thread from
        run_once — an unlocked read-modify-write here would race it
        (VT008's inferred lock/field map; the witness shim asserts the
        same map at runtime)."""
        with self._qlock:
            self.counters[key] += n

    def has_pending(self) -> bool:
        return bool(self._queue)

    def _drain(self) -> List[str]:
        with self._qlock:
            uids = list(self._queue)
            self._queue.clear()
            self._queued.clear()
            self.wake.clear()
        return uids

    # -- eligibility -------------------------------------------------------

    def _classify(self, job) -> Tuple[Optional[list], str]:
        """(pending tasks to place, "") when express-eligible, else
        (None, reason). Caller holds the cache lock."""
        if job is None:
            return None, "gone"
        if job.uid in self.denylist:
            return None, "denylisted"
        if job.uid in self.outstanding:
            return None, "outstanding"
        pg = job.pod_group
        if pg is None:
            return None, "no_podgroup"
        if pg.status.phase not in _ADMITTED:
            return None, "not_admitted"
        if job.queue not in self.cache.queues:
            return None, "no_queue"
        pending = job.task_status_index.get(TaskStatus.PENDING)
        if not pending:
            return None, "no_pending"
        if len(job.tasks) > self.max_tasks:
            return None, "too_many_tasks"
        if job.min_available > self.max_gang:
            return None, "gang_too_big"
        if len(job.tasks) < job.min_available:
            return None, "incomplete"  # more pods still materializing
        tasks = []
        for uid in sorted(pending):
            t = pending[uid]
            if t.node_name:
                return None, "pending_bound"
            if t.resreq.is_empty():
                return None, "best_effort"
            if t.resreq.scalar_resources or t.init_resreq.scalar_resources:
                return None, "scalar_resources"
            pod = t.pod
            if pod is None:
                return None, "no_pod"
            sig, ports, aff = pod_encode_traits(pod)
            if sig != "<plain>" or ports or aff:
                return None, "constraints"
            if any(v.persistent_volume_claim for v in pod.spec.volumes):
                return None, "volumes"
            tasks.append(t)
        # serial task order within the job: priority desc, creation, uid
        tasks.sort(key=lambda t: (
            -t.priority,
            t.pod.metadata.creation_timestamp if t.pod else 0, t.uid))
        return tasks, ""

    # -- the fast path -----------------------------------------------------

    def run_once(self) -> Dict:
        """Service the arrival queue once: classify, place, commit.
        Returns the report dict (always; zero-queued calls are cheap)."""
        t0 = time.perf_counter()
        rep = ExpressReport()
        uids = self._drain()
        rep.queued = len(uids)
        if not uids:
            return rep.as_dict()
        reason = None
        if self._park_reason is not None:
            reason = f"parked:{self._park_reason}"
        elif not self.enabled:
            reason = "lane_disabled"
        elif not self.breaker.allow():
            reason = "circuit_open"
        if reason is not None:
            rep.deferred = len(uids)
            rep.reasons[reason] = len(uids)
            self._count("deferred", len(uids))
            metrics.register_express_deferred(len(uids))
            return rep.as_dict()
        try:
            self._run_batch(uids, rep)
        except Exception:
            # any device/encode failure defers the whole batch to the next
            # full session — express is an accelerator, never a gate; the
            # breaker turns PERSISTENT failure into an auto-park
            # (express_disabled rung) instead of a doomed dispatch per wake
            logger.exception("express batch failed; deferring to session")
            self._count("errors", 1)
            self.breaker.record_failure()
            rep.deferred += rep.queued - rep.placed - rep.deferred
            rep.reasons["error"] = rep.reasons.get("error", 0) + 1
        else:
            if rep.batches:
                self.breaker.record_success()
        rep.ms = (time.perf_counter() - t0) * 1e3
        self.latencies_ms.append(rep.ms)
        metrics.observe_express_latency(rep.ms / 1e3)
        return rep.as_dict()

    def _run_batch(self, uids: List[str], rep: ExpressReport) -> None:
        from volcano_tpu.express import place as place_mod
        from volcano_tpu.express.commit import commit_batch
        from volcano_tpu.utils import devprof

        cache = self.cache
        with cache._lock:
            jobs: List[Tuple[object, list]] = []
            budget = place_mod.EXPRESS_MAX_BATCH
            total = 0
            for uid in uids:
                job = cache.jobs.get(uid)
                tasks, reason = self._classify(job)
                if tasks is None:
                    rep.deferred += 1
                    rep.reasons[reason] = rep.reasons.get(reason, 0) + 1
                    continue
                if total + len(tasks) > budget:
                    # re-enqueue past the batch budget; the next wake
                    # services them (bounded latency beats one huge batch)
                    self.note_arrival(uid)
                    continue
                jobs.append((job, tasks))
                total += len(tasks)
            rows = self.state.refresh() if jobs else []
        if not jobs:
            self._count("deferred", rep.deferred)
            if rep.deferred:
                metrics.register_express_deferred(rep.deferred)
            return

        # serial job order across the batch: priority desc, uid tie-break
        # (creation order — uids are ns/name and submissions are named
        # monotonically; the full session's tie rank agrees)
        jobs.sort(key=lambda jt: (-jt[0].priority, jt[0].uid))

        with devprof.session(rep.profile):
            dev = self.state.stage(rows)
            assign, fulls = self._dispatch(place_mod, dev, jobs)
        rep.full_sweep_steps = fulls
        node_names = self.state.axis.names
        placed, deferred = commit_batch(cache, self, jobs, assign,
                                        node_names)
        rep.placed = placed
        rep.deferred += deferred
        rep.batches = 1
        with self._qlock:
            self.counters["placed"] += placed
            self.counters["deferred"] += rep.deferred
            self.counters["batches"] += 1
        if placed:
            metrics.register_express_placements(placed)
        if rep.deferred:
            metrics.register_express_deferred(rep.deferred)

    def _dispatch(self, place_mod, dev, jobs) -> Tuple[np.ndarray, int]:
        """Encode the batch arrays, run the kernel, fetch the packed
        result. Buckets come off the solver ladder so repeat arrivals of
        any size up to the bucket reuse one compiled program."""
        from volcano_tpu.ops.solver import _bucket
        from volcano_tpu.scheduler.plugins import nodeorder as nodeorder_mod
        from volcano_tpu.utils import devprof

        n_tasks = sum(len(ts) for _, ts in jobs)
        tb = _bucket(max(n_tasks, 1))
        jb = _bucket(max(len(jobs), 1))
        task_req = np.zeros((tb, 2))
        task_initreq = np.zeros((tb, 2))
        task_valid = np.zeros(tb, bool)
        task_job = np.zeros(tb, np.int32)
        task_has_pod = np.ones(tb, bool)
        job_need = np.full(jb, np.iinfo(np.int32).max, np.int32)
        ti = 0
        for ji, (job, tasks) in enumerate(jobs):
            job_need[ji] = len(tasks)  # all-or-nothing per job
            for t in tasks:
                task_req[ti] = (t.resreq.milli_cpu, t.resreq.memory)
                task_initreq[ti] = (t.init_resreq.milli_cpu,
                                    t.init_resreq.memory)
                task_valid[ti] = True
                task_job[ti] = ji
                ti += 1
        nzc = np.where(task_req[:, 0] != 0, task_req[:, 0],
                       nodeorder_mod.DEFAULT_MILLI_CPU_REQUEST)
        nzm = np.where(task_req[:, 1] != 0, task_req[:, 1],
                       nodeorder_mod.DEFAULT_MEMORY_REQUEST)
        weights = np.array([1.0, 1.0])  # default-conf nodeorder weights
        spec = place_mod.ExpressSpec(
            tb=tb, jb=jb,
            window_k=place_mod.window_for(self.state.n, tb))
        wait = devprof.start_fetch(place_mod.solve_express(
            spec, dev["idle"], dev["alloc"], dev["cnt"], dev["ok"],
            dev["maxt"], task_initreq, task_req, nzc, nzm, task_valid,
            task_job, task_has_pod, job_need, weights))
        out = wait()
        return np.asarray(out[:tb]), int(out[tb])

    # -- summaries ---------------------------------------------------------

    def latency_percentiles(self) -> Dict[str, float]:
        lat = sorted(self.latencies_ms)
        if not lat:
            return {"p50": 0.0, "p99": 0.0, "max": 0.0}

        def pick(q):
            return round(lat[min(int(q * len(lat)), len(lat) - 1)], 3)

        return {"p50": pick(0.5), "p99": pick(0.99),
                "max": round(lat[-1], 3)}

    def summary(self) -> Dict:
        return {"counters": dict(self.counters),
                "latency_ms": self.latency_percentiles(),
                "state": dict(self.state.stats) if self.state else {},
                "outstanding": len(self.outstanding),
                "parked": self._park_reason or "",
                "breaker": self.breaker.state}
