"""Express placement kernel — one narrow windowed round on device.

The full session solves placement in bulk-synchronous rounds over the
whole pending set (ops/rounds.py). An express batch is the opposite shape:
a handful of freshly arrived tasks against a long-lived node axis that is
already resident on device. One jitted dispatch does the whole thing:

1. batch-wide masked scores over the node axis (the same fused
   least-requested + balanced-resource scoring the serial loop and the
   rounds kernel use — ``ops.kernels.fused_scores``), one ``lax.top_k``
   candidate window per task (width off the solver bucket ladder,
   vclint VT002's window-size contract);
2. a sequential walk over the (tiny, bucketed) task axis in the serial
   visit order: per step, feasibility + FRESH scores are recomputed on the
   task's window columns only — the express analog of the rounds solver's
   dirty-column rescoring — and the best surviving candidate wins with the
   serial tie-break (lowest node index among maxima);
3. a per-step coverage check proves the windowed answer equals the
   full-width one: placements only shrink idle, so every node outside the
   window is bounded above by the window's last initial score — a fresh
   in-window winner strictly above that bound cannot be beaten outside.
   Uncovered steps (or steps whose window ran dry) take a full-width
   fresh sweep instead, counted in the profile tail (the exactness
   fallback, exactly rounds.py's contract);
4. a gang strip retires every job that could not place ALL of its batch
   tasks (express is all-or-nothing per job — partial gangs are deferred
   to the full session, never half-committed).

The kernel never mutates persistent device state: the committed binds flow
through the real cache effectors host-side, the SnapshotKeeper marks the
touched rows, and the next express refresh patches exactly those rows
(express/encode.py). Result is ONE packed int32 array (assign + profile
tail) so the lane pays a single D2H fetch.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from volcano_tpu.ops.kernels import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    fused_scores,
)
from volcano_tpu.ops.solver import _bucket

# packed-result tail: [full_sweep_steps, placed_total]
PROF_TAIL = 2

EXPRESS_MAX_BATCH = 64


class ExpressSpec(NamedTuple):
    """Static (trace-time) express-solve configuration — the jit key.

    ``tb``/``jb`` are the PADDED task/job buckets (solver._bucket), so
    steady-state repeat arrivals of any size up to the bucket reuse one
    compiled program; ``window_k`` comes off the same ladder (0 = full
    width, the small-axis and parity mode)."""

    tb: int
    jb: int
    window_k: int = 0
    check_pod_count: bool = True
    # fused_scores flags: express models the default conf's nodeorder
    # scoring; binpack sessions are outside the express envelope
    # (trigger.py gates on plugin names), so the flag exists only to keep
    # the shared scorer's signature honest
    use_nodeorder: bool = True
    use_binpack: bool = False


def window_for(n_nodes: int, batch: int) -> int:
    """Candidate-window width for an express batch, off the solver bucket
    ladder (VT002: top_k's k is jit-static; an unbucketed k re-keys the
    program per churn). 0 (full width) when the window would span most of
    the axis anyway — pruning buys nothing below a few hundred nodes."""
    k = _bucket(max(32, 4 * batch))
    if 2 * k > n_nodes:
        return 0
    return k


def task_bucket(n_tasks: int) -> int:
    return _bucket(max(n_tasks, 1))


@functools.partial(jax.jit, static_argnames=("spec",))
def solve_express(spec: ExpressSpec, idle, alloc, cnt, ok, maxt,
                  task_initreq, task_req, task_nzc, task_nzm,
                  task_valid, task_job, task_has_pod, job_need, weights):
    """One express round. Node arrays are the device-resident live axis
    (express/encode.py); task/job arrays are the bucketed arrival batch.

    Returns one packed int32 [tb + PROF_TAIL]: per-task node index (or -1
    deferred), then [full-width fallback steps, placed count].
    """
    n = idle.shape[0]
    tb = spec.tb
    dt = idle.dtype
    eps = jnp.array([MIN_MILLI_CPU, MIN_MEMORY], dt)
    neg = jnp.array(-jnp.inf, dt)

    # scoring context for the shared fused scorer: no affinity signatures
    # in the express envelope (trigger gates on <plain> pods), so the
    # signature axis collapses to one zero row
    aff = jnp.zeros((1, n), dt)
    enc = {
        "least_req_weight": weights[0],
        "balanced_weight": weights[1],
        "node_affinity_weight": jnp.zeros((), dt),
        "affinity_score": aff,
        "node_alloc": alloc,
    }
    sig = jnp.zeros((tb,), jnp.int32)

    used0 = alloc - idle
    scores0 = fused_scores(spec, enc, used0, task_req, task_nzc, task_nzm,
                           sig)                                  # [tb, N]
    scores0 = jnp.where(ok[None, :], scores0, neg)

    if spec.window_k > 0:
        top_s, top_i = lax.top_k(scores0, spec.window_k)         # [tb, W]
        top_i = top_i.astype(jnp.int32)

    def fresh_full(idle_c, cnt_c, t):
        """Full-width fresh feasibility + scores for task t (the
        exactness fallback and the window_k == 0 path)."""
        fit = jnp.all(task_initreq[t][None, :] < idle_c + eps[None, :],
                      axis=-1) & ok
        if spec.check_pod_count:
            fit = fit & ((cnt_c < maxt) | ~task_has_pod[t])
        sc = fused_scores(spec, enc, alloc - idle_c, task_req[t],
                          task_nzc[t], task_nzm[t], jnp.int32(0))
        node = jnp.argmax(jnp.where(fit, sc, neg)).astype(jnp.int32)
        return node, fit[node]

    def body(t, st):
        idle_c, cnt_c, assign, job_placed, fulls, placed_n = st
        valid = task_valid[t]
        req = task_req[t]

        if spec.window_k > 0:
            cols = top_i[t]                                      # [W]
            idle_w = idle_c[cols]
            fit_w = jnp.all(task_initreq[t][None, :] < idle_w + eps[None, :],
                            axis=-1) & ok[cols]
            if spec.check_pod_count:
                fit_w = fit_w & ((cnt_c[cols] < maxt[cols])
                                 | ~task_has_pod[t])
            sc_w = fused_scores(
                spec, enc, alloc[cols] - idle_w, task_req[t],
                task_nzc[t], task_nzm[t], jnp.int32(0),
                alloc=alloc[cols], aff=aff[:, cols])             # [W]
            sc_wm = jnp.where(fit_w, sc_w, neg)
            best_w = jnp.argmax(sc_wm)
            any_w = jnp.any(fit_w)
            # coverage: idle only shrinks inside the dispatch, so every
            # out-of-window node's fresh score <= its initial score <= the
            # window's last initial value; a strictly-greater in-window
            # winner is provably the full-width winner (ties fall back —
            # the full-width tie-break may prefer a lower out-of-window
            # index)
            covered = any_w & (sc_wm[best_w] > top_s[t, spec.window_k - 1])
            need_full = valid & ~covered

            node_f, ok_f = lax.cond(
                need_full,
                lambda _: fresh_full(idle_c, cnt_c, t),
                lambda _: (jnp.int32(0), jnp.bool_(False)), None)
            node = jnp.where(covered, cols[best_w], node_f)
            feas = jnp.where(covered, any_w, ok_f)
            fulls = fulls + need_full.astype(jnp.int32)
        else:
            node, feas = fresh_full(idle_c, cnt_c, t)
            fulls = fulls + valid.astype(jnp.int32)

        place = valid & feas
        dreq = jnp.where(place, req, jnp.zeros_like(req)).astype(dt)
        idle_c = idle_c.at[node].add(-dreq)
        cnt_c = cnt_c.at[node].add(place.astype(jnp.int32))
        assign = assign.at[t].set(jnp.where(place, node, jnp.int32(-1)))
        job_placed = job_placed.at[task_job[t]].add(place.astype(jnp.int32))
        return (idle_c, cnt_c, assign, job_placed, fulls,
                placed_n + place.astype(jnp.int32))

    st = (idle, cnt, jnp.full((tb,), -1, jnp.int32),
          jnp.zeros((spec.jb,), jnp.int32), jnp.int32(0), jnp.int32(0))
    idle_c, cnt_c, assign, job_placed, fulls, placed_n = lax.fori_loop(
        0, tb, body, st)

    # all-or-nothing per job: a batch job that could not place EVERY task
    # is stripped (deferred to the full session) — express never commits a
    # partial gang, and the strip needs no capacity refund because the
    # kernel's idle/cnt are discarded (the cache is mutated only by the
    # host commit of surviving placements)
    short = job_placed < job_need
    stripped = short[task_job] & (assign >= 0)
    assign = jnp.where(stripped, jnp.int32(-1), assign)
    placed_n = placed_n - jnp.sum(stripped.astype(jnp.int32))

    return jnp.concatenate([
        assign, jnp.stack([fulls, placed_n])]).astype(jnp.int32)
