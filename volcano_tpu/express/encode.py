"""Express live state — dirty-row maintenance of the node axis between
sessions, plus the device-resident buffer cache the express kernel solves
against.

The SnapshotKeeper's axis belongs to the SESSION snapshot and is only
reconciled at ``snapshot()`` time. The express lane places between
sessions, from the CACHE's live truth, so it maintains its own columnar
axis over the live NodeInfo objects and keeps the derived solve buffers
resident on device:

- a dirty-set **shadow** registered with the SnapshotKeeper
  (snapkeeper.add_shadow) receives every mark the keeper receives —
  watch handlers, bind/evict effectors, bulk-apply syncs — without
  consuming the keeper's own sets;
- ``refresh()`` (caller holds the cache lock) drains the shadow: marked
  rows are patched in place via the shared ``nodeaxis.refresh_rows``, an
  accounting-generation sweep catches in-place mutations that have no
  mark (the deferred mirror flush), and membership changes fall back to a
  full recapture — exactly the keeper's own honesty ladder;
- ``stage()`` ships ONLY the patched rows to the device: a bucketed
  index + row-value scatter through a tiny jitted patch kernel, so the
  per-arrival h2d budget is O(rows the cluster actually changed), not
  O(nodes). A full rebuild (first use, membership change, generation
  bump) re-puts the axis wholesale and is counted separately.

Nothing here requires jax until ``stage()`` runs; a jax-free host can
still construct the state (the lane then defers everything).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from volcano_tpu.scheduler.cache.nodeaxis import (
    F_BLOCKING_TAINTS,
    F_NET_UNAVAILABLE,
    F_READY,
    F_UNSCHEDULABLE,
    capture_node_axis,
    refresh_rows,
)

# flags a node must / must not carry to take express placements — the
# static half of the default predicate chain (encoder._static_node_ok
# with the pressure checks at their default-off conf)
_BAD_FLAGS = int(F_NET_UNAVAILABLE) | int(F_UNSCHEDULABLE) \
    | int(F_BLOCKING_TAINTS)


def _ok_col(flags: np.ndarray) -> np.ndarray:
    return ((flags & F_READY) != 0) & ((flags & np.uint16(_BAD_FLAGS)) == 0)


class ExpressState:
    """Live node axis + device buffer cache for one SchedulerCache."""

    # dirty-row budget: past this fraction of the axis a wholesale re-put
    # is cheaper than the scatter (and the patch bucket ladder stops
    # paying for itself)
    PATCH_FRACTION = 4

    def __init__(self, cache):
        self.cache = cache
        self.shadow = cache.snap_keeper.add_shadow()
        self.axis = None
        self._index: Dict[str, int] = {}
        self._seen_generation = -1
        self.dev: Optional[dict] = None
        # host twin of the staged column values (ops/replica.py mirror
        # idiom): a marked row whose visible columns did not actually move
        # is dropped before the scatter
        self._mirror: Optional[dict] = None
        self.n = 0
        self.stats = {"rebuilds": 0, "row_patches": 0, "patched_rows": 0,
                      "h2d_puts": 0, "rows_deduped": 0}

    def detach(self) -> None:
        self.cache.snap_keeper.drop_shadow(self.shadow)

    # -- host refresh (caller holds the cache lock) ------------------------

    def _rebuild(self) -> None:
        ready = {name: nd for name, nd in self.cache.nodes.items()
                 if nd.ready()}
        self.axis = capture_node_axis(ready)
        self._index = {name: i for i, name in enumerate(self.axis.names)}
        self._seen_generation = self.shadow.generation
        self.shadow.dirty_nodes.clear()
        self.n = len(self.axis.names)
        self.dev = None  # stage() re-puts wholesale
        self.stats["rebuilds"] += 1

    def refresh(self) -> list:
        """Reconcile the axis with the live cache; returns the patched row
        indices (empty after a wholesale rebuild — ``self.dev is None``
        then signals stage() to re-put)."""
        axis = self.axis
        if axis is None or self._seen_generation != self.shadow.generation:
            self._rebuild()
            return []

        dirty = self.shadow.dirty_nodes
        self.shadow.dirty_nodes = set()
        updates: Dict[int, object] = {}
        index = self._index
        for name in sorted(dirty):
            nd = self.cache.nodes.get(name)
            ready = nd is not None and nd.ready()
            if ready != (name in index):
                self._rebuild()  # membership changed
                return []
            if ready:
                updates[index[name]] = nd
        # unmarked in-place churn: the deferred mirror flush mutates cache
        # twins without a dirty mark; every such mutation bumps _acct_gen,
        # so a generation sweep over the shared live objects catches it
        n = len(axis.nodes)
        if n:
            cur = np.fromiter((nd._acct_gen for nd in axis.nodes),
                              np.int64, n)
            for i in np.nonzero(cur != axis.gens)[0].tolist():
                updates.setdefault(i, axis.nodes[i])
        if not updates:
            return []
        rows = sorted(updates.items())
        if not refresh_rows(axis, rows):
            self._rebuild()  # new scalar dimension reshapes columns
            return []
        # a row whose readiness flag flipped without an add/delete mark
        # (e.g. an OutOfSync trip) changes the ok column, which the patch
        # path carries — no special case needed
        self.stats["row_patches"] += 1
        self.stats["patched_rows"] += len(rows)
        if self.dev is not None and len(rows) * self.PATCH_FRACTION > self.n:
            self.dev = None  # wholesale re-put beats a huge scatter
        return [i for i, _ in rows]

    # -- host columns ------------------------------------------------------

    def _host_cols(self, rows=None):
        """(idle, alloc, cnt, ok, maxt) as dense arrays — full axis, or
        gathered for the given row indices."""
        axis = self.axis
        if rows is None:
            sel = slice(None)
        else:
            sel = np.asarray(rows, np.int32)
        idle = np.stack([axis.cpu["idle"][sel], axis.mem["idle"][sel]],
                        axis=1)
        alloc = np.stack([axis.cpu["alloc"][sel], axis.mem["alloc"][sel]],
                         axis=1)
        cnt = axis.node_cnt[sel].astype(np.int32)
        ok = _ok_col(axis.flags[sel])
        maxt = axis.max_tasks[sel].astype(np.int32)
        return idle, alloc, cnt, ok, maxt

    # -- device staging ----------------------------------------------------

    def stage(self, rows: list) -> dict:
        """Device twins of the axis columns: wholesale put on rebuild,
        dirty-row scatter otherwise. Returns the device buffer dict.

        The scatter is the session replica's shared bucketed kernel
        (ops/replica.scatter_rows) — one row-patch program family for the
        whole codebase — and the lane keeps a host mirror of the staged
        values, so a marked row whose columns did not actually move (the
        bulk-apply echo of a placement the lane itself committed and
        already patched, a status-only generation bump) is dropped before
        it re-crosses the link: no more re-patching rows whose staged
        values the last session already landed."""
        import jax

        from volcano_tpu.ops import replica as replica_mod

        cols = ("idle", "alloc", "cnt", "ok", "maxt")
        if self.dev is None:
            self._mirror = dict(zip(cols, self._host_cols()))
            self.dev = {k: jax.device_put(v)
                        for k, v in self._mirror.items()}
            self.stats["h2d_puts"] += len(self.dev)
            return self.dev
        if rows:
            sel = np.asarray(rows, np.int32)
            vals = dict(zip(cols, self._host_cols(sel)))
            keep = None
            for k, v in vals.items():
                d = v != self._mirror[k][sel]
                if d.ndim > 1:
                    d = d.any(axis=1)
                keep = d if keep is None else (keep | d)
            live = [r for r, kp in zip(rows, keep) if kp]
            self.stats["rows_deduped"] += len(rows) - len(live)
            if not live:
                return self.dev
            idx = replica_mod.bucket_pad_rows(live)
            pvals = dict(zip(cols, self._host_cols(idx)))
            self.dev = replica_mod.scatter_rows(self.dev, idx, pvals)
            for k in cols:
                self._mirror[k][idx] = pvals[k]
            self.stats["h2d_puts"] += 6  # idx + five row blocks
        return self.dev
