"""Express live state — dirty-row maintenance of the node axis between
sessions, plus the device-resident buffer cache the express kernel solves
against.

The SnapshotKeeper's axis belongs to the SESSION snapshot and is only
reconciled at ``snapshot()`` time. The express lane places between
sessions, from the CACHE's live truth, so it maintains its own columnar
axis over the live NodeInfo objects and keeps the derived solve buffers
resident on device:

- a dirty-set **shadow** registered with the SnapshotKeeper
  (snapkeeper.add_shadow) receives every mark the keeper receives —
  watch handlers, bind/evict effectors, bulk-apply syncs — without
  consuming the keeper's own sets;
- ``refresh()`` (caller holds the cache lock) drains the shadow: marked
  rows are patched in place via the shared ``nodeaxis.refresh_rows``, an
  accounting-generation sweep catches in-place mutations that have no
  mark (the deferred mirror flush), and membership changes fall back to a
  full recapture — exactly the keeper's own honesty ladder;
- ``stage()`` ships ONLY the patched rows to the device: a bucketed
  index + row-value scatter through a tiny jitted patch kernel, so the
  per-arrival h2d budget is O(rows the cluster actually changed), not
  O(nodes). A full rebuild (first use, membership change, generation
  bump) re-puts the axis wholesale and is counted separately.

Nothing here requires jax until ``stage()`` runs; a jax-free host can
still construct the state (the lane then defers everything).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from volcano_tpu.scheduler.cache.nodeaxis import (
    F_BLOCKING_TAINTS,
    F_NET_UNAVAILABLE,
    F_READY,
    F_UNSCHEDULABLE,
    capture_node_axis,
    refresh_rows,
)

# flags a node must / must not carry to take express placements — the
# static half of the default predicate chain (encoder._static_node_ok
# with the pressure checks at their default-off conf)
_BAD_FLAGS = int(F_NET_UNAVAILABLE) | int(F_UNSCHEDULABLE) \
    | int(F_BLOCKING_TAINTS)


def _ok_col(flags: np.ndarray) -> np.ndarray:
    return ((flags & F_READY) != 0) & ((flags & np.uint16(_BAD_FLAGS)) == 0)


class ExpressState:
    """Live node axis + device buffer cache for one SchedulerCache."""

    # dirty-row budget: past this fraction of the axis a wholesale re-put
    # is cheaper than the scatter (and the patch bucket ladder stops
    # paying for itself)
    PATCH_FRACTION = 4

    def __init__(self, cache):
        self.cache = cache
        self.shadow = cache.snap_keeper.add_shadow()
        self.axis = None
        self._index: Dict[str, int] = {}
        self._seen_generation = -1
        self.dev: Optional[dict] = None
        self.n = 0
        self.stats = {"rebuilds": 0, "row_patches": 0, "patched_rows": 0,
                      "h2d_puts": 0}

    def detach(self) -> None:
        self.cache.snap_keeper.drop_shadow(self.shadow)

    # -- host refresh (caller holds the cache lock) ------------------------

    def _rebuild(self) -> None:
        ready = {name: nd for name, nd in self.cache.nodes.items()
                 if nd.ready()}
        self.axis = capture_node_axis(ready)
        self._index = {name: i for i, name in enumerate(self.axis.names)}
        self._seen_generation = self.shadow.generation
        self.shadow.dirty_nodes.clear()
        self.n = len(self.axis.names)
        self.dev = None  # stage() re-puts wholesale
        self.stats["rebuilds"] += 1

    def refresh(self) -> list:
        """Reconcile the axis with the live cache; returns the patched row
        indices (empty after a wholesale rebuild — ``self.dev is None``
        then signals stage() to re-put)."""
        axis = self.axis
        if axis is None or self._seen_generation != self.shadow.generation:
            self._rebuild()
            return []

        dirty = self.shadow.dirty_nodes
        self.shadow.dirty_nodes = set()
        updates: Dict[int, object] = {}
        index = self._index
        for name in sorted(dirty):
            nd = self.cache.nodes.get(name)
            ready = nd is not None and nd.ready()
            if ready != (name in index):
                self._rebuild()  # membership changed
                return []
            if ready:
                updates[index[name]] = nd
        # unmarked in-place churn: the deferred mirror flush mutates cache
        # twins without a dirty mark; every such mutation bumps _acct_gen,
        # so a generation sweep over the shared live objects catches it
        n = len(axis.nodes)
        if n:
            cur = np.fromiter((nd._acct_gen for nd in axis.nodes),
                              np.int64, n)
            for i in np.nonzero(cur != axis.gens)[0].tolist():
                updates.setdefault(i, axis.nodes[i])
        if not updates:
            return []
        rows = sorted(updates.items())
        if not refresh_rows(axis, rows):
            self._rebuild()  # new scalar dimension reshapes columns
            return []
        # a row whose readiness flag flipped without an add/delete mark
        # (e.g. an OutOfSync trip) changes the ok column, which the patch
        # path carries — no special case needed
        self.stats["row_patches"] += 1
        self.stats["patched_rows"] += len(rows)
        if self.dev is not None and len(rows) * self.PATCH_FRACTION > self.n:
            self.dev = None  # wholesale re-put beats a huge scatter
        return [i for i, _ in rows]

    # -- host columns ------------------------------------------------------

    def _host_cols(self, rows=None):
        """(idle, alloc, cnt, ok, maxt) as dense arrays — full axis, or
        gathered for the given row indices."""
        axis = self.axis
        if rows is None:
            sel = slice(None)
        else:
            sel = np.asarray(rows, np.int32)
        idle = np.stack([axis.cpu["idle"][sel], axis.mem["idle"][sel]],
                        axis=1)
        alloc = np.stack([axis.cpu["alloc"][sel], axis.mem["alloc"][sel]],
                         axis=1)
        cnt = axis.node_cnt[sel].astype(np.int32)
        ok = _ok_col(axis.flags[sel])
        maxt = axis.max_tasks[sel].astype(np.int32)
        return idle, alloc, cnt, ok, maxt

    # -- device staging ----------------------------------------------------

    def stage(self, rows: list) -> dict:
        """Device twins of the axis columns: wholesale put on rebuild,
        dirty-row scatter otherwise. Returns the device buffer dict."""
        import jax

        from volcano_tpu.ops.solver import _bucket

        if self.dev is None:
            idle, alloc, cnt, ok, maxt = self._host_cols()
            self.dev = {
                "idle": jax.device_put(idle),
                "alloc": jax.device_put(alloc),
                "cnt": jax.device_put(cnt),
                "ok": jax.device_put(ok),
                "maxt": jax.device_put(maxt),
            }
            self.stats["h2d_puts"] += len(self.dev)
            return self.dev
        if rows:
            db = _bucket(max(len(rows), 1))
            # padding repeats the first dirty row — duplicate scatter
            # writes of identical values, benign exactly as in
            # rounds._rescore_dirty
            padded = [rows[0]] * (db - len(rows)) + list(rows)
            idx = np.asarray(padded, np.int32)
            idle, alloc, cnt, ok, maxt = self._host_cols(padded)
            self.dev = dict(zip(
                ("idle", "alloc", "cnt", "ok", "maxt"),
                _patch_rows(self.dev["idle"], self.dev["alloc"],
                            self.dev["cnt"], self.dev["ok"],
                            self.dev["maxt"], idx,
                            idle, alloc, cnt, ok, maxt)))
            self.stats["h2d_puts"] += 6  # idx + five row blocks
        return self.dev


def _patch_rows(idle, alloc, cnt, ok, maxt, idx,
                idle_r, alloc_r, cnt_r, ok_r, maxt_r):
    """Scatter dirty rows into the device-resident columns. Jitted lazily
    (import-time jax dependence would break jax-free hosts)."""
    global _patch_rows_jit
    if _patch_rows_jit is None:
        import jax

        def patch(idle, alloc, cnt, ok, maxt, idx,
                  idle_r, alloc_r, cnt_r, ok_r, maxt_r):
            return (idle.at[idx].set(idle_r), alloc.at[idx].set(alloc_r),
                    cnt.at[idx].set(cnt_r), ok.at[idx].set(ok_r),
                    maxt.at[idx].set(maxt_r))

        _patch_rows_jit = jax.jit(patch)
    return _patch_rows_jit(idle, alloc, cnt, ok, maxt, idx,
                           idle_r, alloc_r, cnt_r, ok_r, maxt_r)


_patch_rows_jit = None
